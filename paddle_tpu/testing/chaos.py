"""Deterministic fault injection for fault-tolerance testing.

The checkpoint writer (and any other crash-hardened I/O path) funnels its
file opens through :func:`checked_open` and sprinkles :func:`inject` calls
at named sites.  With no fault armed both are a single list/dict lookup —
production cost is nil.  Tests arm faults through context managers:

* :func:`truncate_writes` — a file opened for writing whose path contains
  ``match`` accepts only the first ``at_byte`` bytes, then raises (the
  on-disk file is left truncated exactly there: a process killed
  mid-``np.savez``).
* :func:`fail_open` — the Nth matching :func:`checked_open` call raises
  (transient filesystem error).
* :func:`fail_at` — the Nth :func:`inject(site)` call raises (transient
  dataset / network error at an arbitrary instrumented site).
* :func:`flip_bytes` / :func:`truncate_file` — immediate post-write
  corruption of a file on disk (bit rot / torn tail; also the
  export-file corruption lever for the serving prefix-cache restart
  path — the manifest re-hash must catch either).
* :func:`run_to_step_and_kill` — spawn a subprocess and deliver a signal
  the moment it prints ``STEP <n>`` (kill-at-step-N for resume tests).

Serving chaos (ISSUE 15) rides the same site pattern:

* :func:`fail_at` on the serving dispatch sites
  (``serving.prefill.dispatch`` / ``serving.tick.dispatch``) injects a
  dispatch failure on the Nth program call.
* :func:`nan_logits` — arm non-finite logits for specific slots and/or
  request ids; the engine consults :func:`nan_payload` at the points it
  holds host logits (prefill row, host-sampling decode rows) and
  corrupts the armed rows, simulating a NaN-producing forward the
  flight-recorder watchdog then detects.
* :func:`delay_at` / :func:`maybe_delay` — stall an instrumented site
  (``serving.harvest``) for a fixed number of seconds: the
  deterministic "hung block_until_ready" the tick watchdog
  (``FLAGS_serving_tick_timeout_s``) must catch.

Fleet chaos (ISSUE 16) adds the router's proxy leg:

* :func:`fail_at` on ``fleet.proxy.connect`` makes the router's Nth
  upstream POST fail before any bytes reach the replica — the
  connect-level outage the failover path (retry the next replica in
  rendezvous order) must absorb with zero dropped requests, which is
  exactly what the rolling-restart gate in tests/test_fleet.py injects
  mid-drill.

Elastic chaos (ISSUE 20) instruments the supervision layer:

* :func:`fail_at` on ``store.request`` injects a transient socket-level
  failure into every TCPStore request — the EPIPE-mid-rendezvous the
  store's bounded retry/backoff (``FLAGS_store_retries``) must absorb.
* :func:`fail_at` on ``elastic.lease.publish`` silences a launcher's
  heartbeat lease without killing the process — peers must observe the
  lease expire and bump ``restart_generation`` (simulated node death).
* :func:`delay_at` on ``elastic.step`` freezes a worker's step
  heartbeat in :class:`ProgressReporter.publish` — the deterministic
  wedged-collective the launcher's progress watchdog
  (``FLAGS_elastic_stall_timeout_s``) must convert into kill + restart.

Everything is counted: each armed fault records how often it fired so a
test can assert the injection actually happened.
"""

from __future__ import annotations

import builtins
import os
import signal
import subprocess
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = [
    "checked_open", "inject", "active_faults",
    "truncate_writes", "fail_open", "fail_at",
    "flip_bytes", "truncate_file", "run_to_step_and_kill",
    "nan_logits", "nan_payload", "delay_at", "maybe_delay",
]

_lock = threading.Lock()


class Fault:
    """One armed fault.  ``fires`` counts actual injections."""

    def __init__(self, kind: str, match: str = "", at_byte: int = 0,
                 on_calls: Optional[Sequence[int]] = None,
                 exc_factory: Optional[Callable[[], BaseException]] = None):
        self.kind = kind                # "truncate" | "open" | "site"
        self.match = match
        self.at_byte = at_byte
        # 1-based call numbers that fire; None = every matching call
        self.on_calls = set(on_calls) if on_calls is not None else None
        self.exc_factory = exc_factory or (
            lambda: OSError(f"chaos: injected fault ({kind}:{match})"))
        self.calls = 0
        self.fires = 0

    def should_fire(self) -> bool:
        self.calls += 1
        hit = self.on_calls is None or self.calls in self.on_calls
        if hit:
            self.fires += 1
        return hit


_open_faults: List[Fault] = []
_site_faults: Dict[str, Fault] = {}
_nan_faults: List[Fault] = []
_delay_faults: Dict[str, Fault] = {}


def active_faults() -> int:
    return (len(_open_faults) + len(_site_faults) + len(_nan_faults)
            + len(_delay_faults))


class _TruncatingFile:
    """File wrapper that accepts ``at_byte`` bytes then raises — the write
    that crosses the limit is cut exactly at the boundary first, so the
    on-disk state is a mid-write crash, not a clean short file."""

    def __init__(self, f, at_byte: int, exc_factory):
        self._f = f
        self._room = at_byte
        self._exc_factory = exc_factory
        self._dead = False

    def write(self, data):
        if self._dead:
            return 0  # the crash already propagated; cleanup writes vanish
        n = len(data)
        if n <= self._room:
            self._room -= n
            return self._f.write(data)
        if self._room > 0:
            self._f.write(data[:self._room])
            self._room = 0
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dead = True
        raise self._exc_factory()

    def seek(self, *a, **kw):
        if self._dead or self._f.closed:
            return 0  # silence zipfile/np.savez __del__ cleanup
        return self._f.seek(*a, **kw)

    def tell(self):
        if self._dead or self._f.closed:
            return 0
        return self._f.tell()

    def flush(self):
        if not (self._dead or self._f.closed):
            self._f.flush()

    def close(self):
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __getattr__(self, name):
        return getattr(self._f, name)


def checked_open(path, mode: str = "rb", **kw):
    """``open`` with armed write faults applied.  The production fast path
    is one truthiness check on the (normally empty) fault list."""
    if _open_faults:
        spath = os.fspath(path)
        with _lock:
            for fault in list(_open_faults):
                if fault.match not in spath:
                    continue
                if fault.kind == "open":
                    if fault.should_fire():
                        raise fault.exc_factory()
                elif fault.kind == "truncate" and any(
                        c in mode for c in "wxa+"):
                    if fault.should_fire():
                        return _TruncatingFile(
                            builtins.open(path, mode, **kw),
                            fault.at_byte, fault.exc_factory)
    return builtins.open(path, mode, **kw)


def inject(site: str) -> None:
    """Raise at an instrumented site if a matching fault is armed."""
    if not _site_faults:
        return
    with _lock:
        fault = _site_faults.get(site)
        fire = fault is not None and fault.should_fire()
    if fire:
        raise fault.exc_factory()


@contextmanager
def truncate_writes(match: str, at_byte: int,
                    on_calls: Optional[Sequence[int]] = None,
                    exc: type = OSError):
    """Arm a mid-write truncation for files whose path contains ``match``."""
    fault = Fault("truncate", match, at_byte, on_calls,
                  lambda: exc(f"chaos: write truncated at byte {at_byte} "
                              f"({match})"))
    with _lock:
        _open_faults.append(fault)
    try:
        yield fault
    finally:
        with _lock:
            _open_faults.remove(fault)


@contextmanager
def fail_open(match: str, on_calls: Optional[Sequence[int]] = None,
              exc: type = OSError):
    """Arm an open-time failure for paths containing ``match`` (1-based
    matching-call numbers in ``on_calls``; None = every call)."""
    fault = Fault("open", match, 0, on_calls,
                  lambda: exc(f"chaos: open failed ({match})"))
    with _lock:
        _open_faults.append(fault)
    try:
        yield fault
    finally:
        with _lock:
            _open_faults.remove(fault)


@contextmanager
def fail_at(site: str, on_calls: Optional[Sequence[int]] = None,
            exc: type = OSError):
    """Arm :func:`inject(site)` to raise on the given call numbers."""
    fault = Fault("site", site, 0, on_calls,
                  lambda: exc(f"chaos: injected failure at {site!r}"))
    with _lock:
        if site in _site_faults:
            raise RuntimeError(f"chaos: site {site!r} already armed")
        _site_faults[site] = fault
    try:
        yield fault
    finally:
        with _lock:
            _site_faults.pop(site, None)


@contextmanager
def nan_logits(site: str = "", slots: Sequence[int] = (),
               rids: Sequence[int] = (),
               on_calls: Optional[Sequence[int]] = None):
    """Arm non-finite logits for the given slots and/or request ids at
    ``site`` ('' matches every site).  The engine's host-logits screens
    call :func:`nan_payload` and corrupt a matching row in place — the
    deterministic stand-in for a NaN-producing forward."""
    fault = Fault("nan", site, 0, on_calls)
    fault.slots = set(int(s) for s in slots)
    fault.rids = set(int(r) for r in rids)
    with _lock:
        _nan_faults.append(fault)
    try:
        yield fault
    finally:
        with _lock:
            _nan_faults.remove(fault)


def nan_payload(site: str, slot: Optional[int] = None,
                rid: Optional[int] = None) -> bool:
    """Should the caller's host logits row for (slot, rid) go
    non-finite?  One truthiness check when nothing is armed."""
    if not _nan_faults:
        return False
    with _lock:
        for fault in _nan_faults:
            if fault.match and fault.match != site:
                continue
            if (slot in fault.slots) or (rid in fault.rids):
                if fault.should_fire():
                    return True
    return False


@contextmanager
def delay_at(site: str, seconds: float,
             on_calls: Optional[Sequence[int]] = None):
    """Arm a wall-clock stall at an instrumented :func:`maybe_delay`
    site (e.g. ``serving.harvest``) — the deterministic hung-device
    injection the serving tick watchdog must detect."""
    fault = Fault("delay", site, 0, on_calls)
    fault.seconds = float(seconds)
    with _lock:
        if site in _delay_faults:
            raise RuntimeError(f"chaos: delay site {site!r} already armed")
        _delay_faults[site] = fault
    try:
        yield fault
    finally:
        with _lock:
            _delay_faults.pop(site, None)


def maybe_delay(site: str) -> None:
    """Sleep at an instrumented site if a delay fault is armed (a plain
    dict truthiness check otherwise)."""
    if not _delay_faults:
        return
    with _lock:
        fault = _delay_faults.get(site)
        fire = fault is not None and fault.should_fire()
        seconds = fault.seconds if fire else 0.0
    if fire:
        time.sleep(seconds)


def flip_bytes(path: str, offset: int, count: int = 1,
               xor: int = 0xFF) -> None:
    """XOR ``count`` bytes at ``offset`` in place (post-write bit rot)."""
    with builtins.open(path, "r+b") as f:
        f.seek(offset)
        data = bytearray(f.read(count))
        if not data:
            raise ValueError(f"{path}: offset {offset} is past EOF")
        for i in range(len(data)):
            data[i] ^= xor
        f.seek(offset)
        f.write(bytes(data))


def truncate_file(path: str, nbytes: int) -> None:
    """Truncate a file on disk to ``nbytes`` (torn tail)."""
    with builtins.open(path, "r+b") as f:
        f.truncate(nbytes)


def run_to_step_and_kill(cmd: Sequence[str], step: int,
                         marker: str = "STEP ",
                         sig: int = signal.SIGKILL,
                         timeout: float = 300.0,
                         env: Optional[Dict[str, str]] = None,
                         cwd: Optional[str] = None) -> "subprocess.CompletedProcess[str]":
    """Run ``cmd``; the moment a stdout line starts with ``marker`` and
    names a step >= ``step``, deliver ``sig``.  Returns a CompletedProcess
    whose stdout holds everything printed (so tests can assert how far the
    child got before dying).  The child must print ``STEP <n>`` per step
    with line buffering (``flush=True``)."""
    proc = subprocess.Popen(
        list(cmd), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, bufsize=1, env=env, cwd=cwd)
    lines: List[str] = []
    signalled = False
    try:
        assert proc.stdout is not None
        for line in proc.stdout:
            lines.append(line)
            s = line.strip()
            if not signalled and s.startswith(marker):
                try:
                    n = int(s[len(marker):].split()[0])
                except (ValueError, IndexError):
                    continue
                if n >= step:
                    proc.send_signal(sig)
                    signalled = True
        rc = proc.wait(timeout=timeout)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    return subprocess.CompletedProcess(list(cmd), rc, "".join(lines), "")
