"""paddle.static.amp — mixed-precision surface for the static facade.

Parity: `python/paddle/static/amp/` (decorator.py decorate,
fp16_lists.py AutoMixedPrecisionLists/CustomOpLists, fp16_utils.py
cast_model_to_fp16/cast_parameters_to_fp16/fp16_guard).

TPU-native seat: the static Program here is a record-replay facade over
the SAME eager dispatch the dynamic AMP hooks instrument, so static AMP
*is* dynamic AMP — `decorate` wraps the optimizer with the shared
GradScaler/auto_cast machinery, the op lists feed the same white/black
sets, and the fp16 casts rewrite parameter storage the way the
inference passes do.  (The reference maintains a parallel
program-rewriting implementation because its static graph executes in
C++; there is no second executor to rewrite here.)
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ...amp import auto_cast  # the context-manager class
from ...amp.auto_cast import FP16_BLACK_LIST, FP16_WHITE_LIST

__all__ = ["decorate", "AutoMixedPrecisionLists", "CustomOpLists",
           "cast_model_to_fp16", "cast_parameters_to_fp16", "fp16_guard"]


class AutoMixedPrecisionLists:
    """White/black op-name lists.  Parity: fp16_lists.py
    AutoMixedPrecisionLists(custom_white_list, custom_black_list,
    custom_black_varnames)."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None, dtype="float16"):
        self.white_list = set(FP16_WHITE_LIST)
        self.black_list = set(FP16_BLACK_LIST)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)
        self.black_varnames = set(custom_black_varnames or ())
        self.dtype = dtype


CustomOpLists = AutoMixedPrecisionLists


class _DecoratedOptimizer:
    """Optimizer wrapper running minimize/step under auto_cast with the
    decorated lists + loss scaling.  Parity: decorator.py
    OptimizerWithMixedPrecision (amp_init folded into construction)."""

    def __init__(self, optimizer, amp_lists, level, dtype,
                 init_loss_scaling, use_dynamic_loss_scaling,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 incr_ratio=2.0, decr_ratio=0.8):
        self._inner = optimizer
        self._lists = amp_lists or AutoMixedPrecisionLists(dtype=dtype)
        self._level = level
        self._dtype = dtype
        from ...amp.grad_scaler import GradScaler
        self._scaler = GradScaler(
            init_loss_scaling=init_loss_scaling,
            incr_every_n_steps=incr_every_n_steps,
            decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
            incr_ratio=incr_ratio, decr_ratio=decr_ratio,
            use_dynamic_loss_scaling=use_dynamic_loss_scaling)

    def _ctx(self):
        return auto_cast(
            True, custom_white_list=self._lists.white_list,
            custom_black_list=self._lists.black_list,
            level=self._level, dtype=self._dtype)

    def amp_init(self, place=None, scope=None, test_program=None,
                 use_fp16_test=False):
        pass  # casts happen at dispatch; nothing to pre-rewrite

    def backward(self, loss, **kw):
        scaled = self._scaler.scale(loss)
        scaled.backward()
        return []

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        # GradScaler.step() already runs the scale-update state machine
        # internally — calling update() again would double-advance it
        self.backward(loss)
        self._scaler.step(self._inner)
        self._inner.clear_grad()
        return [], []

    def step(self):
        self._scaler.step(self._inner)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def decorate(optimizer, amp_lists=None, level="O1", dtype="float16",
             init_loss_scaling=2.0 ** 15, incr_every_n_steps=1000,
             decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=None, use_amp_guard=None,
             use_master_grad=False, use_promote=False,
             master_weight=None, **kw):
    """Parity: static/amp/decorator.py decorate."""
    if use_dynamic_loss_scaling is None:
        use_dynamic_loss_scaling = dtype == "float16"
    return _DecoratedOptimizer(optimizer, amp_lists, level, dtype,
                               init_loss_scaling, use_dynamic_loss_scaling,
                               incr_every_n_steps=incr_every_n_steps,
                               decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
                               incr_ratio=incr_ratio, decr_ratio=decr_ratio)


def cast_model_to_fp16(program_or_layer, amp_lists=None,
                       use_fp16_guard=True, dtype="float16", **kw):
    """Cast a Layer's floating parameters to the reduced dtype (the
    static pass rewrites the program's var dtypes; the facade's
    equivalent storage rewrite).  Parity: fp16_utils.cast_model_to_fp16."""
    from ...amp.auto_cast import _cast_model_keep_norms
    target = jnp.bfloat16 if dtype == "bfloat16" else jnp.float16
    # shared O2 cast: norm layers stay fp32 (the reference's static pass
    # keeps black-list ops fp32 for the same running-stat reason)
    _cast_model_keep_norms(program_or_layer, target)
    return program_or_layer


def cast_parameters_to_fp16(place, program_or_layer, scope=None,
                            to_fp16_var_names=None, dtype="float16"):
    """Parity: fp16_utils.cast_parameters_to_fp16 (positional `place`
    matches the reference's signature; unused on TPU)."""
    return cast_model_to_fp16(program_or_layer, dtype=dtype)


@contextlib.contextmanager
def fp16_guard():
    """Region marker: ops inside run under auto_cast O1 (the reference
    tags program regions for the fp16 pass).  Parity: fp16_utils.fp16_guard."""
    with auto_cast(True, level="O1", dtype="float16"):
        yield
