"""Engine X-ray (ISSUE 14): the per-program execution ledger, sampled
device-time probe, cost_analysis join, HLO kernel-coverage audit,
per-tick phase breakdown, readiness, and the chrome-trace export.

The acceptance story: a warmed CPU-smoke serving run names every grid
program in `dump --xray` with dispatches, sampled device seconds,
cost-analysis FLOPs and MFU; the kernel-coverage table correctly
reports the dense-gather (non-Pallas) status of this build's serving
paths; sampling changes NO streams and forces tick-loop boundaries
(never measuring through the double-buffered chain); and the full
spec+quant+TP2+chunked composition still triggers zero post-warmup
compiles with sampling enabled.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.flags import flag_guard
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
from paddle_tpu.observability import compile_tracker, dump
from paddle_tpu.observability import flight_recorder as flight
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import xray


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt3_tiny())
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _sampling_off_after():
    yield
    paddle.set_flags({"xray_sample_interval": 0})


# ------------------------------------------------------------- unit layer

def test_key_for_uses_scalar_signature_pairs_only():
    """Ledger keys = compile-tracker name + the blame signature's
    SCALAR pairs; bulky values (the fused step's per-leaf aval tuple)
    are dropped so keys stay readable and bounded."""
    assert xray.key_for("serving.tick",
                        (("steps_per_tick", 2), ("max_batch", 4))) \
        == "serving.tick[steps_per_tick=2,max_batch=4]"
    assert xray.key_for("optimizer.fused_step",
                        (("leaves", 3), ("params", ("f32[4]", "f32[2]")),
                         ("donate", True))) \
        == "optimizer.fused_step[leaves=3,donate=True]"
    assert xray.key_for("plain", None) == "plain"
    long = "x" * 40
    assert xray.key_for("n", (("s", long),)) == "n"   # long strs dropped


def test_dispatch_counts_always_samples_on_interval():
    ent = xray.register("t.xray_unit", (("case", 1),))
    fn = jax.jit(lambda a: a * 2 + 1)
    fn(jnp.ones((4,)))   # compile outside the counted window
    n0 = ent.dispatches
    with flag_guard(xray_sample_interval=2):
        for i in range(4):
            out = xray.dispatch(ent, fn, (jnp.ones((4,)) * i,), {})
    np.testing.assert_allclose(np.asarray(out), np.ones(4) * 7)
    assert ent.dispatches - n0 == 4
    assert ent.samples == 2          # dispatches 2 and 4
    assert ent.sampled_seconds > 0 and ent.min_s <= ent.max_s
    # sampling off: counting continues, sampling stops
    xray.dispatch(ent, fn, (jnp.ones((4,)),), {})
    assert ent.dispatches - n0 == 5 and ent.samples == 2


def test_wrap_first_call_registers_and_never_samples_the_compile():
    fn = compile_tracker.wrap_first_call(
        jax.jit(lambda x: x + 1), "t.xray_wfc", (("v", 7),))
    ent = fn._xray_entry
    assert ent.key == "t.xray_wfc[v=7]"
    with flag_guard(xray_sample_interval=1):
        fn(jnp.ones((2,)))
        # first call = trace + XLA compile: a dispatch, never a sample
        assert ent.dispatches == 1 and ent.samples == 0
        assert xray.sample_due(fn)   # the next dispatch would probe
        fn(jnp.ones((2,)))
        assert ent.dispatches == 2 and ent.samples == 1
    assert not xray.sample_due(fn)   # off: nothing is ever due
    assert not xray.sample_due(None)


def test_attach_lowered_cost_and_custom_call_audit():
    lowered = jax.jit(lambda a, b: a @ b).lower(
        jnp.ones((8, 8)), jnp.ones((8, 8)))
    ent = xray.register("t.xray_cost")
    xray.attach_lowered(ent, lowered)
    assert ent.audited
    assert ent.flops and ent.flops > 0
    assert ent.bytes_accessed and ent.bytes_accessed > 0
    assert ent.pallas is False and ent.custom_calls == 0
    # attach never raises on junk
    xray.attach_lowered(ent, object())
    xray.attach_lowered(None, lowered)


# -------------------------------------------------- the warmed-engine core

def test_warmed_engine_ledger_mfu_coverage_and_dump(model, capsys):
    """THE acceptance core on a fast 3-program grid: after warmup +
    traffic with sampling at interval 1, every warmed program appears
    in the ledger (and `dump --xray`) with dispatches, sampled device
    seconds, cost-analysis FLOPs and a positive MFU; the coverage
    table reports the dense (non-Pallas) status of every program on
    this CPU build; sampling triggered ZERO extra compiles (the
    warmup-grid pin extended); and the engine's health flips ready."""
    with flag_guard(serving_warmup=True, serving_pad_buckets="16",
                    xray_sample_interval=1):
        eng = ServingEngine(model, max_batch=2, max_context=64,
                            block_size=16, steps_per_tick=1,
                            prefix_cache=False)
        assert eng.ready is False
        assert eng.health() == {"ready": False, "reason": "warmup"}
        eng.warmup()
        before = compile_tracker.total_compiles()
        rng = np.random.RandomState(7)
        r1 = eng.add_request(Request(rng.randint(1, 1000, (10,)),
                                     max_new_tokens=5))
        r2 = eng.add_request(Request(rng.randint(1, 1000, (12,)),
                                     max_new_tokens=5, do_sample=True,
                                     temperature=0.9, seed=3))
        eng.run()
        assert compile_tracker.total_compiles() == before
        assert r1.done and r2.done
    assert eng.ready is True and eng.health()["ready"] is True
    assert eng.health()["warmup"]["programs"] == 3

    rep = xray.report()
    base = "max_batch=2,block_size=16"
    keys = {
        "serving.tick": f"serving.tick[steps_per_tick=1,{base}]",
        "serving.prefill": f"serving.prefill[L_pad=16,{base}]",
        "serving.decode":
            f"serving.decode[variant=host_sampling_k1,{base}]"}
    by_key = {p["program"]: p for p in rep["programs"]}
    by_prefix = {name: by_key[key] for name, key in keys.items()}
    mine = list(by_prefix.values())
    # every warmed grid program is named, with the full evidence row
    for name in ("serving.tick", "serving.prefill", "serving.decode"):
        p = by_prefix[name]
        assert p["dispatches"] > 0, name
        assert p["samples"] > 0, name
        assert p["sampled_device_s"] > 0, name
        assert p["flops_per_dispatch"] > 0, name
        assert p["bytes_per_dispatch"] > 0, name
        assert p["mfu"] > 0, name
        assert p["achieved_gflops_per_s"] > 0, name
    # fractions are a distribution over the estimated device time
    fracs = [p["device_time_frac"] for p in rep["programs"]
             if p["device_time_frac"]]
    assert 0.99 < sum(fracs) < 1.01
    # the CPU build lowers NO serving path to a Pallas custom call
    cov = {c["program"]: c for c in rep["kernel_coverage"]}
    for name in ("serving.tick", "serving.prefill", "serving.decode"):
        row = cov[by_prefix[name]["program"]]
        assert row["pallas"] is False and row["custom_calls"] == 0
        assert row["path"]     # a human-readable serving-path label
    # stats() exports the same ledger
    st = eng.stats()["xray"]
    assert st["programs_tracked"] == rep["programs_tracked"]
    assert st["total_est_device_s"] > 0
    # /metrics exports the dispatch/device-seconds counters
    disp = obs_metrics.get("xray.program_dispatches_total")
    assert disp.value(program=by_prefix["serving.tick"]["program"]) > 0
    dev = obs_metrics.get("xray.program_device_seconds_total")
    assert dev.value(program=by_prefix["serving.tick"]["program"]) > 0
    # ...and `dump --xray` prints the very same document
    assert dump.main(["--xray"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "paddle_tpu.xray/v1"
    assert {p["program"] for p in doc["programs"]} \
        >= {p["program"] for p in mine}
    assert doc["kernel_coverage"]


def test_sampling_parity_forced_boundaries_and_phases(model):
    """Sampling parity + the overlap contract + the phase breakdown,
    on two engines (tier-1 budget: one shared pair instead of three):
    identical token streams with sampling off vs every-dispatch,
    interval=1 forces EVERY tick to a real boundary
    (overlap_dispatches stays flat — no probe ever times a chained
    dispatch), and the tick flight records carry the ISSUE 14 phases.
    Sparse-interval composition is covered by the @slow composition
    pin (interval=2) and the cold_start spec+quant pin."""
    def drive(interval):
        eng = ServingEngine(model, max_batch=2, max_context=64,
                            block_size=16, steps_per_tick=2,
                            prefix_cache=False)
        rng = np.random.RandomState(3)
        with flag_guard(xray_sample_interval=interval,
                        serving_overlap=True):
            reqs = [eng.add_request(
                        Request(rng.randint(1, 1000, (10,)),
                                max_new_tokens=7)),
                    eng.add_request(
                        Request(rng.randint(1, 1000, (12,)),
                                max_new_tokens=7, do_sample=True,
                                seed=5))]
            eng.run()
        return [list(r.output_ids) for r in reqs]

    ov = obs_metrics.get("serving.overlap_dispatches")
    base = drive(0)
    assert ov.total() > 0          # the base run really overlapped
    # the per-tick phase breakdown rides the flight-record tick events
    recs = [r for r in flight.default_recorder().steps()
            if r.get("timeline") == "serving"]
    assert recs
    rec = recs[-1]
    assert rec["t_unix"] > 0
    ph = rec["phases"]
    for key in ("schedule_ms", "chunk_prefill_ms", "dispatch_ms",
                "harvest_wait_ms", "emit_ms", "host_ms",
                "device_wait_ms"):
        assert ph[key] >= 0, key
    assert ph["dispatch_ms"] > 0 and ph["host_ms"] >= ph["dispatch_ms"]
    assert ph["device_wait_ms"] == ph["harvest_wait_ms"]
    ov0 = ov.total()
    assert drive(1) == base        # parity at every-dispatch sampling
    assert ov.total() == ov0       # ...with every boundary forced


# ------------------------------------------------------------ chrome trace

def _flight_doc():
    """A synthetic flight document shaped like a real serving run."""
    t = 1700000000.0
    return {
        "schema": "paddle_tpu.flight/v1", "pid": 42, "reason": "manual",
        "steps": [
            {"timeline": "serving", "step": 3, "t_unix": t + 1.0,
             "wall_s": 0.5, "tokens": 4, "active": 2, "decode_steps": 2,
             "overlap": False,
             "phases": {"schedule_ms": 20.0, "chunk_prefill_ms": 30.0,
                        "dispatch_ms": 100.0, "harvest_wait_ms": 40.0,
                        "emit_ms": 10.0, "host_ms": 160.0,
                        "device_wait_ms": 40.0}},
            {"timeline": "training", "step": 9},       # skipped
            {"timeline": "serving", "step": 4, "wall_s": 0.1},  # no stamp
        ],
        "events": [
            {"kind": "request", "outcome": "finished", "rid": 7,
             "unix_time": t + 1.2, "e2e_s": 0.9, "queue_wait_s": 0.1,
             "prefill_s": 0.2, "ttft_s": 0.3, "prompt_len": 12,
             "tokens_out": 6, "ticks": 3, "prefill_chunks": 2},
            {"kind": "prefill_chunk", "rid": 7, "unix_time": t + 0.5,
             "start": 0, "tokens": 8, "slot": 0, "done": False},
            {"kind": "request", "outcome": "rejected:capacity",
             "rid": 8},                                # skipped
        ]}


def test_chrome_trace_nests_requests_under_the_tick_timeline():
    from paddle_tpu.observability import chrome
    trace = chrome.trace_from_flight(_flight_doc())
    evs = trace["traceEvents"]
    x = [e for e in evs if e["ph"] == "X"]
    names = {e["name"] for e in x}
    assert "tick 3" in names
    # the un-stamped tick and the training record are skipped, never
    # guessed
    assert "tick 4" not in names and "tick 9" not in names
    tick = next(e for e in x if e["name"] == "tick 3")
    phases = [e for e in x if e["cat"] == "phase"]
    assert {p["name"] for p in phases} == {
        "schedule", "chunk_prefill", "dispatch", "harvest_wait", "emit"}
    for p in phases:   # nested inside the tick slice, same row
        assert p["tid"] == tick["tid"]
        assert tick["ts"] <= p["ts"]
        assert p["ts"] + p["dur"] <= tick["ts"] + tick["dur"] + 1
    # request lifecycle: whole span + children on its own row
    req = next(e for e in x if e["name"] == "request 7")
    assert req["tid"] != tick["tid"]
    kids = [e for e in x if e["tid"] == req["tid"] and e is not req]
    assert {k["name"] for k in kids} == {"queue_wait", "prefill",
                                         "decode"}
    for k in kids:
        assert req["ts"] <= k["ts"] <= req["ts"] + req["dur"]
    # ticks and requests share the wall-clock timeline
    assert abs((tick["ts"] + tick["dur"]) - (req["ts"] + req["dur"])) \
        < 0.5 * 1e6
    # the chunk instant landed on the request's row
    chunk = next(e for e in evs if e["ph"] == "i")
    assert chunk["tid"] == req["tid"] and chunk["args"]["tokens"] == 8
    # rows are named for the viewer
    tn = {e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert {"ticks", "request 7"} <= tn
    json.dumps(trace)            # chrome JSON must serialize


def test_dump_cli_chrome_roundtrip(tmp_path, capsys):
    """`dump --chrome --path f.json` converts a written flight dump to
    chrome trace JSON on stdout (the PR 2 span round-trip, extended to
    the serving timeline)."""
    rec = flight.FlightRecorder(capacity=8)
    doc = _flight_doc()
    for s in doc["steps"]:
        rec.record_step(s)
    for e in doc["events"]:
        rec.record_event(e.pop("kind"), **e)
    path = tmp_path / "flight_chrome.json"
    rec.dump(str(path))
    assert dump.main(["--chrome", "--path", str(path)]) == 0
    out = capsys.readouterr().out
    trace = json.loads(out)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "tick 3" in names and "request 7" in names
    assert trace["otherData"]["schema"] == "paddle_tpu.chrome_trace/v1"


# -------------------------------------------------- composition (heavy)

@pytest.mark.slow   # warms a TP2 x ngram-spec x chunked grid (~8 shard
                    # map compiles) — tier-1 keeps the 3-program pin fast
def test_composition_spec_quant_tp2_chunked_ledger_pinned(model):
    """ISSUE 14 satellite: ledger correctness under the FULL serving
    composition — ngram spec (adaptive 2-rung ladder) + int8 quant +
    TP2 + chunked prefill + prefix cache, sampling at interval 2.
    Zero post-warmup compiles with sampling enabled; the ledger's
    dispatch counts reconcile exactly against the engine's own
    counters; spec verify and suffix prefill carry sampled MFU and
    their kernel-claim audit rows (via=interpret on this CPU build)."""
    with flag_guard(serving_warmup=True, serving_pad_buckets="16,32",
                    serving_prefill_chunk=8, xray_sample_interval=2):
        # max_batch=3 keeps this engine's ledger keys unique across the
        # process (entries are process-global; other TP2 tests in a
        # full run use max_batch 2/4)
        eng = ServingEngine(model, max_batch=3, max_context=128,
                            block_size=16, steps_per_tick=2,
                            tp_degree=2, spec_decode=True,
                            spec_draft="ngram", spec_adaptive=True,
                            spec_k_ladder="2,4", quant="int8")
        info = eng.warmup()
        before = compile_tracker.total_compiles()
        rng = np.random.RandomState(13)
        pat = list(rng.randint(1, 1000, (4,)))
        reqs = [eng.add_request(Request(np.array(pat * 10),
                                        max_new_tokens=20)),
                eng.add_request(Request(rng.randint(1, 1000, (24,)),
                                        max_new_tokens=8)),
                eng.add_request(Request(rng.randint(1, 1000, (40,)),
                                        max_new_tokens=8,
                                        do_sample=True, seed=2))]
        eng.run()
        assert compile_tracker.total_compiles() == before
        assert all(r.done for r in reqs)
        assert eng.spec_ticks > 0 and eng.prefill_chunks_total > 0

        rep = xray.report()
        tp = [p for p in rep["programs"]
              if p["program"].endswith("max_batch=3,block_size=16,tp=2]")]
        spec = [p for p in tp
                if p["program"].startswith("serving.spec_tick")]
        cont = [p for p in tp
                if p["program"].startswith("serving.prefill_cont")]
        # counts pinned against the engine's own accounting: one ledger
        # dispatch per spec tick + the per-rung warmup validation run;
        # one per prefill chunk + the per-bucket validation run
        assert sum(p["dispatches"] for p in spec) \
            == eng.spec_ticks + len(eng.spec_ladder)
        assert sum(p["dispatches"] for p in cont) \
            == eng.prefill_chunks_total + len(eng.pad_ladder)
        assert info["programs"] == len(tp)
        # sampled MFU present on the hot programs
        hot = max(spec, key=lambda p: p["dispatches"])
        assert hot["samples"] > 0 and hot["mfu"] and hot["mfu"] > 0
        # both ROADMAP 5b suspects now run the paged Pallas kernels
        # (ISSUE 18): no custom call on this CPU build (interpret mode
        # is traced XLA), but the trace-time claims channel flips the
        # rows to kernel=True via=interpret — and the dense-gather
        # note is gone
        cov = {c["program"]: c for c in rep["kernel_coverage"]}
        for p in spec:
            row = cov[p["program"]]
            assert row["pallas"] is False
            assert row["kernel"] is True and row["via"] == "interpret"
            assert "paged_spec_verify" in row["kernels"]
            assert "note" not in row
        for p in cont:
            row = cov[p["program"]]
            assert row["pallas"] is False
            assert row["kernel"] is True and row["via"] == "interpret"
            assert "paged_chunk_prefill" in row["kernels"]
            assert "note" not in row
        assert cov[hot["program"]]["path"] == "spec verify chunk"
