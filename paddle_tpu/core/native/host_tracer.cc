// Host event tracer: native span recorder behind paddle_tpu.profiler.
//
// Role of the reference's HostEventRecorder/HostTracer
// (`paddle/fluid/platform/profiler/host_tracer.cc`, ring buffers of
// RecordEvent spans, merged into the chrome trace): each thread owns an
// event buffer + string arena guarded by its own (uncontended in steady
// state) mutex, registered once in a global list.  Dumps are INCREMENTAL:
// ht_dump emits only events recorded since the previous dump, so draining
// the trace mid-run neither resets epochs nor retires buffers.  Python
// (ctypes) drives it through the C ABI below.
//
// Build: paddle_tpu.core.native.build("host_tracer") -> cached .so.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Event {
  uint32_t name_idx;
  uint32_t cat_idx;
  double start;  // seconds, caller's clock base
  double end;
};

struct ThreadBuf {
  std::mutex mu;  // owner thread vs dumping thread; uncontended otherwise
  std::vector<Event> events;
  std::deque<std::string> names;  // deque: stable addresses across growth
  size_t dumped = 0;              // events[0:dumped] already emitted
  uint64_t tid;
};

std::mutex g_mu;
std::vector<ThreadBuf*> g_bufs;
std::vector<ThreadBuf*> g_stale;  // retired by ht_start; kept allocated —
                                  // a racing thread may still hold a pointer
std::atomic<bool> g_enabled{false};
std::atomic<uint64_t> g_epoch{1};

thread_local ThreadBuf* t_buf = nullptr;
thread_local uint64_t t_epoch = 0;

ThreadBuf* buf_for_thread() {
  uint64_t epoch = g_epoch.load(std::memory_order_acquire);
  if (t_buf == nullptr || t_epoch != epoch) {
    auto* b = new ThreadBuf();
    static std::atomic<uint64_t> next_tid{1};
    b->tid = next_tid.fetch_add(1);
    b->events.reserve(1 << 12);
    std::lock_guard<std::mutex> lk(g_mu);
    g_bufs.push_back(b);
    t_buf = b;
    t_epoch = epoch;
  }
  return t_buf;
}

}  // namespace

extern "C" {

// Start a fresh recording epoch.  Old buffers are retired, not freed: a
// thread racing an ht_record may still write into its stale buffer — the
// write lands in memory that stays valid and is simply never dumped.
// Epochs are per profiler *session* (not per dump), so g_stale growth is
// bounded by sessions x threads.
void ht_start() {
  std::lock_guard<std::mutex> lk(g_mu);
  for (auto* b : g_bufs) g_stale.push_back(b);
  g_bufs.clear();
  g_epoch.fetch_add(1, std::memory_order_release);
  g_enabled.store(true, std::memory_order_release);
}

void ht_stop() { g_enabled.store(false, std::memory_order_release); }

int ht_enabled() { return g_enabled.load(std::memory_order_acquire); }

// Record a completed span (timestamps in the caller's clock domain).
void ht_record(const char* name, const char* cat, double start, double end) {
  if (!g_enabled.load(std::memory_order_acquire)) return;
  ThreadBuf* b = buf_for_thread();
  std::lock_guard<std::mutex> lk(b->mu);
  b->names.emplace_back(name);
  uint32_t name_idx = static_cast<uint32_t>(b->names.size() - 1);
  b->names.emplace_back(cat);
  uint32_t cat_idx = static_cast<uint32_t>(b->names.size() - 1);
  b->events.push_back(Event{name_idx, cat_idx, start, end});
}

// Append events recorded since the previous dump as TSV
// (tid \t category \t start \t end \t name) and return how many were
// written (-1: cannot open path).  Safe against concurrent recorders:
// each buffer is visited under its own mutex.
long ht_dump(const char* path) {
  std::FILE* f = std::fopen(path, "w");
  if (f == nullptr) return -1;
  long n = 0;
  std::lock_guard<std::mutex> lk(g_mu);
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> bl(b->mu);
    for (size_t i = b->dumped; i < b->events.size(); i++) {
      const Event& e = b->events[i];
      std::fprintf(f, "%llu\t%s\t%.9f\t%.9f\t%s\n",
                   (unsigned long long)b->tid, b->names[e.cat_idx].c_str(),
                   e.start, e.end, b->names[e.name_idx].c_str());
      n++;
    }
    b->dumped = b->events.size();
  }
  std::fclose(f);
  return n;
}

long ht_event_count() {
  std::lock_guard<std::mutex> lk(g_mu);
  long n = 0;
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> bl(b->mu);
    n += (long)b->events.size();
  }
  return n;
}

}  // extern "C"
