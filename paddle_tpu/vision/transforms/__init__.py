"""Vision transforms (numpy host-side). Parity: `python/paddle/vision/transforms/`."""

from __future__ import annotations

import numbers

import numpy as np

from ...framework.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad", "to_tensor", "normalize"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def to_tensor(pic, data_format="CHW"):
    raw = np.asarray(pic)
    arr = raw.astype(np.float32)
    if arr.ndim == 2:
        arr = arr[None] if data_format == "CHW" else arr[..., None]
    elif arr.ndim == 3 and data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
        arr = arr.transpose(2, 0, 1)
    if raw.dtype == np.uint8:  # keyed on dtype, not pixel values
        arr = arr / 255.0
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, pic):
        return to_tensor(pic, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        img = np.asarray(img._value)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    return Tensor((img - mean) / std)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = self.size
        # nearest-neighbor host resize (cheap; bilinear on device via F.interpolate)
        ih, iw = arr.shape[0], arr.shape[1]
        ridx = (np.arange(h) * ih / h).astype(int)
        cidx = (np.arange(w) * iw / w).astype(int)
        return arr[ridx][:, cidx]


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = self.size
        ih, iw = arr.shape[0], arr.shape[1]
        top = (ih - h) // 2
        left = (iw - w) // 2
        return arr[top:top + h, left:left + w]


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pads = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        h, w = self.size
        ih, iw = arr.shape[0], arr.shape[1]
        top = np.random.randint(0, ih - h + 1)
        left = np.random.randint(0, iw - w + 1)
        return arr[top:top + h, left:left + w]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        p = self.padding
        if isinstance(p, int):
            pads = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
        else:
            pads = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pads)
