"""Tensor-parallel (Megatron-style) layers.

Parity: `python/paddle/distributed/fleet/layers/mpu/mp_layers.py`
(VocabParallelEmbedding `:47`, ColumnParallelLinear `:334`, RowParallelLinear
`:541`, ParallelCrossEntropy `:742`).

TPU-native: weights carry `NamedSharding` over the 'mp' mesh axis; the
matmul/identity/allreduce dance of the reference's `_c_identity/_mp_allreduce`
custom-grad ops is GSPMD's job — XLA inserts the all-reduce/all-gather where
the sharding propagation demands, both eagerly (per-op jit) and in captured
graphs.  The layer classes exist so user code and checkpoints match the
reference; the sharding annotation is the whole implementation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework.tensor import Tensor
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer.layers import Layer
from .. import mesh as _mesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy", "shard_param"]


def shard_param(param, *spec):
    """Attach a NamedSharding over the global mesh to a parameter's storage."""
    m = _mesh.get_mesh()
    if m is None:
        return param
    sh = NamedSharding(m, P(*spec))
    param._value = jax.device_put(param._value, sh)
    param._dist_attr = ("mesh", spec)
    return param


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        # vocab dim sharded over mp: each rank holds a vocab shard; the
        # gather's cross-shard fetch becomes an XLA collective
        shard_param(self.weight, "mp", None)

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        shard_param(self.weight, None, "mp")  # columns sharded
        self.bias = self.create_parameter([out_features], is_bias=True) \
            if has_bias else None
        if self.bias is not None:
            shard_param(self.bias, "mp")

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        m = _mesh.get_mesh()
        if self.gather_output and m is not None and _mesh.axis_size("mp") > 1:
            # force replication of the mp-sharded output (all-gather)
            repl = NamedSharding(m, P())
            if out._is_traced():
                out._value = jax.lax.with_sharding_constraint(out._value, repl)
            else:
                out._value = jax.device_put(out._value, repl)
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        shard_param(self.weight, "mp", None)  # rows sharded
        self.bias = self.create_parameter([out_features], is_bias=True) \
            if has_bias else None  # bias replicated (added after reduce)

    def forward(self, x):
        # contraction over the sharded dim -> GSPMD inserts the all-reduce
        out = F.linear(x, self.weight, self.bias)
        return out


class ParallelCrossEntropy(Layer):
    """Cross entropy over vocab-sharded logits.

    The reference splits softmax across the mp group with masked local max /
    sum + allreduces (`mp_ops.py _c_softmax_with_cross_entropy`).  Under GSPMD
    the same fused cross_entropy expression on mp-sharded logits lowers to the
    identical pattern (per-shard max/sum + all-reduce over mp), so this is a
    thin wrapper."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
