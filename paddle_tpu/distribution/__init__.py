"""Probability distributions.  Parity: `python/paddle/distribution/`."""

from .distribution import Distribution
from .distributions import (Bernoulli, Beta, Categorical, Dirichlet,
                            Exponential, Gamma, Geometric, Gumbel, Laplace,
                            LogNormal, Multinomial, Normal, Poisson, Uniform)
from .kl import kl_divergence, register_kl

__all__ = ["Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
           "Beta", "Dirichlet", "Gamma", "Laplace", "Exponential",
           "LogNormal", "Gumbel", "Geometric", "Poisson", "Multinomial",
           "kl_divergence", "register_kl"]
