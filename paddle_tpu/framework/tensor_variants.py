"""Tensor variants: SelectedRows and StringTensor.

Parity: `paddle/phi/core/selected_rows.h` (row-sparse value holder used by
sparse embedding gradients and distributed lookup tables) and
`paddle/phi/core/string_tensor.h` (pstring array for text preprocessing
ops).

TPU-native notes: XLA has no sparse buffers — a SelectedRows here is the
COO-by-rows pair (int rows, dense [n, ...] values) living as two jax
arrays; `to_dense`/`apply_to` lower to one scatter(-add), which is exactly
what the reference's SelectedRows ends up doing inside its optimizers.
Embedding gradients stay dense by default (gather transpose = scatter is
already fused by XLA); SelectedRows is provided for API/semantic parity
and as the merge container for PS-style row updates.  StringTensor holds a
numpy object array host-side: strings never ship to the chip; tokenizer
ops consume them on host, which mirrors the reference (string kernels are
CPU-only there too).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor

__all__ = ["SelectedRows", "StringTensor"]


class SelectedRows:
    """Row-sparse tensor: `height` logical rows, of which `rows[i]` holds
    `value[i]` (`selected_rows.h`)."""

    def __init__(self, rows: Sequence[int], value, height: int):
        self._rows = jnp.asarray(np.asarray(rows, np.int32))
        v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        if v.shape[0] != self._rows.shape[0]:
            raise ValueError(
                f"SelectedRows: {self._rows.shape[0]} rows vs value dim0 "
                f"{v.shape[0]}")
        self._value = v
        self._height = int(height)

    @property
    def rows(self):
        return Tensor._wrap(self._rows)

    @property
    def value(self):
        return Tensor._wrap(self._value)

    @property
    def height(self) -> int:
        return self._height

    @property
    def shape(self):
        return [self._height] + list(self._value.shape[1:])

    def has_merged_rows(self) -> bool:
        import numpy as _np
        r = _np.asarray(jax.device_get(self._rows))
        return len(_np.unique(r)) == len(r)

    def merge(self) -> "SelectedRows":
        """Sum duplicate rows (the reference's scatter-merge,
        `phi/kernels/funcs/selected_rows_functor.cc` MergeAdd)."""
        import numpy as _np
        r = _np.asarray(jax.device_get(self._rows))
        uniq, inv = _np.unique(r, return_inverse=True)
        merged = jax.ops.segment_sum(self._value, jnp.asarray(inv),
                                     num_segments=len(uniq))
        return SelectedRows(uniq, merged, self._height)

    def to_dense(self) -> Tensor:
        """One scatter-add into a zero [height, ...] tensor."""
        out = jnp.zeros((self._height,) + self._value.shape[1:],
                        self._value.dtype)
        return Tensor._wrap(out.at[self._rows].add(self._value))

    def apply_to(self, dense: Tensor, scale: float = 1.0) -> Tensor:
        """dense[rows] += scale * value — the optimizer-update form the
        reference's sparse SGD kernel implements."""
        v = dense._value.at[self._rows].add(
            (self._value * scale).astype(dense._value.dtype))
        return Tensor._wrap(v)

    def __repr__(self):
        return (f"SelectedRows(height={self._height}, "
                f"n={int(self._rows.shape[0])}, "
                f"row_shape={tuple(self._value.shape[1:])})")


class StringTensor:
    """Host-side string array (`string_tensor.h` pstring tensor).

    Strings never move to the device; ops over them (lowercasing,
    tokenization) run on host and produce numeric Tensors for the chip.
    """

    def __init__(self, data, name=None):
        arr = np.asarray(data, dtype=object)
        self._data = arr
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return "pstring"

    def numpy(self) -> np.ndarray:
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __len__(self):
        return len(self._data)

    def lower(self) -> "StringTensor":
        return StringTensor(np.vectorize(str.lower, otypes=[object])(
            self._data))

    def upper(self) -> "StringTensor":
        return StringTensor(np.vectorize(str.upper, otypes=[object])(
            self._data))

    def encode_ids(self, vocab: dict, unk_id: int = 0) -> Tensor:
        """Map each string through `vocab` to an int32 id Tensor."""
        ids = np.vectorize(lambda s: vocab.get(s, unk_id),
                           otypes=[np.int32])(self._data)
        return Tensor._wrap(jnp.asarray(ids))

    def __repr__(self):
        return f"StringTensor(shape={self.shape})"
