"""Search/sort/sampling-index ops. Parity: `python/paddle/tensor/search.py`.

Dynamic-output-shape ops (nonzero, masked_select, unique) execute eagerly on
concrete values only — they cannot appear under jit capture, same as the
reference's dy2static graph-break behavior for them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from .registry import dispatch as _d, register_op
from ..core.dtypes import canonical_index_dtype as _ityfn
_ITYPE = _ityfn()

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
    "nonzero", "masked_select", "index_sample", "unique", "unique_consecutive",
    "searchsorted", "bucketize", "median", "nanmedian", "quantile",
    "bincount", "histogramdd",
]


register_op("argmax", lambda x, *, axis, keepdim:
            jnp.argmax(x, axis=axis, keepdims=keepdim).astype(_ITYPE))
register_op("argmin", lambda x, *, axis, keepdim:
            jnp.argmin(x, axis=axis, keepdims=keepdim).astype(_ITYPE))


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _d("argmax", (x,), {"axis": axis if axis is None else int(axis),
                               "keepdim": bool(keepdim)})


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _d("argmin", (x,), {"axis": axis if axis is None else int(axis),
                               "keepdim": bool(keepdim)})


register_op("argsort", lambda x, *, axis, descending:
            (jnp.flip(jnp.argsort(x, axis=axis), axis=axis) if descending
             else jnp.argsort(x, axis=axis)).astype(_ITYPE))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    return _d("argsort", (x,), {"axis": int(axis), "descending": bool(descending)})


register_op("sort", lambda x, *, axis, descending:
            jnp.flip(jnp.sort(x, axis=axis), axis=axis) if descending
            else jnp.sort(x, axis=axis))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    return _d("sort", (x,), {"axis": int(axis), "descending": bool(descending)})


def _topk_fwd(x, *, k, axis, largest):
    if axis != x.ndim - 1 and axis != -1:
        xm = jnp.moveaxis(x, axis, -1)
    else:
        xm = x
    if largest:
        vals, idx = jax.lax.top_k(xm, k)
    else:
        vals, idx = jax.lax.top_k(-xm, k)
        vals = -vals
    if axis != x.ndim - 1 and axis != -1:
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(_ITYPE)


register_op("topk", _topk_fwd)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    if isinstance(k, Tensor):
        k = int(k.item())
    return _d("topk", (x,), {"k": int(k), "axis": int(axis),
                             "largest": bool(largest)})


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    axis = axis % x.ndim
    vals = sort(x, axis=axis)
    idxs = argsort(x, axis=axis)
    from .manipulation import take_along_axis, squeeze
    from .creation import full
    sel = full([1], k - 1, dtype="int64")
    shape = [1] * x.ndim
    from .manipulation import reshape, broadcast_to
    idx_shape = list(x.shape)
    idx_shape[axis] = 1
    gather_idx = broadcast_to(reshape(sel, shape), idx_shape)
    v = take_along_axis(vals, gather_idx, axis)
    i = take_along_axis(idxs, gather_idx, axis)
    if not keepdim:
        v, i = squeeze(v, axis), squeeze(i, axis)
    return v, i


def _mode_fwd(x, *, axis, keepdim):
    """Most-frequent value along axis (paddle.mode / mode op): sort the
    axis, count equal runs with a cummax-style scan-free trick, pick the
    LAST value whose run is maximal (matches the reference's choice of
    the highest value on count ties)."""
    xm = jnp.moveaxis(x, axis, -1)
    s = jnp.sort(xm, axis=-1)
    si = jnp.argsort(xm, axis=-1)
    n = s.shape[-1]
    same = s[..., :, None] == s[..., None, :]          # [..., n, n]
    counts = jnp.sum(same, axis=-1)                    # run length per pos
    # LAST maximal run = highest tied value (the reference's tie rule)
    best = (n - 1) - jnp.argmax(jnp.flip(counts, axis=-1), axis=-1)
    # the last element of that run (highest original index in the run)
    vals = jnp.take_along_axis(s, best[..., None], axis=-1)
    run_last = (n - 1) - jnp.argmax(
        jnp.flip(s == vals, axis=-1), axis=-1)
    v = jnp.take_along_axis(s, run_last[..., None], axis=-1)
    i = jnp.take_along_axis(si, run_last[..., None], axis=-1)
    v = jnp.moveaxis(v, -1, axis)
    i = jnp.moveaxis(i, -1, axis)
    if not keepdim:
        v = jnp.squeeze(v, axis)
        i = jnp.squeeze(i, axis)
    return v, i.astype(_ITYPE)


register_op("mode", _mode_fwd)


def mode(x, axis=-1, keepdim=False, name=None):
    """Parity: python/paddle/tensor/search.py mode (mode op)."""
    return _d("mode", (x,), {"axis": int(axis) % x.ndim
                             if int(axis) < 0 else int(axis),
                             "keepdim": bool(keepdim)})


def nonzero(x, as_tuple=False):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    idx = np.nonzero(v)
    if as_tuple:
        return tuple(Tensor._wrap(jnp.asarray(i, _ITYPE)) for i in idx)
    return Tensor._wrap(jnp.asarray(np.stack(idx, axis=1), _ITYPE))


def masked_select(x, mask, name=None):
    """Dynamic-shape select; indices are resolved eagerly on the host, then the
    pick is a differentiable gather so gradients flow like the reference op."""
    from .manipulation import broadcast_to, flatten, gather
    if not isinstance(x, Tensor):
        x = Tensor(jnp.asarray(x))
    m = np.asarray(mask._value if isinstance(mask, Tensor) else mask)
    out_shape = np.broadcast_shapes(tuple(x.shape), m.shape)
    xb = flatten(broadcast_to(x, list(out_shape)))
    idx = np.nonzero(np.broadcast_to(m, out_shape).reshape(-1))[0].astype(np.int32)
    return gather(xb, Tensor._wrap(jnp.asarray(idx)), axis=0)


def _index_sample_fwd(x, index):
    return jnp.take_along_axis(x, index, axis=1)


register_op("index_sample", _index_sample_fwd)


def index_sample(x, index):
    return _d("index_sample", (x, index), {})


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    res = np.unique(v, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        res = (res,)
    outs = [Tensor._wrap(jnp.asarray(r)) for r in res]
    return outs[0] if len(outs) == 1 else tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    if axis is not None:
        raise NotImplementedError
    flat = v.reshape(-1)
    keep = np.ones(len(flat), bool)
    keep[1:] = flat[1:] != flat[:-1]
    out = [Tensor._wrap(jnp.asarray(flat[keep]))]
    if return_inverse:
        out.append(Tensor._wrap(jnp.asarray(np.cumsum(keep) - 1, np.int64)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, len(flat)))
        out.append(Tensor._wrap(jnp.asarray(counts, np.int64)))
    return out[0] if len(out) == 1 else tuple(out)


register_op("searchsorted", lambda sorted_seq, values, *, right:
            jnp.searchsorted(sorted_seq, values,
                             side="right" if right else "left").astype(_ITYPE))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    out = _d("searchsorted", (sorted_sequence, values), {"right": bool(right)})
    if out_int32:
        from .manipulation import cast
        out = cast(out, "int32")
    return out


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


register_op("median", lambda x, *, axis, keepdim:
            jnp.median(x, axis=axis, keepdims=keepdim))
register_op("nanmedian", lambda x, *, axis, keepdim:
            jnp.nanmedian(x, axis=axis, keepdims=keepdim))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return _d("median", (x,), {"axis": axis if axis is None else int(axis),
                               "keepdim": bool(keepdim)})


def nanmedian(x, axis=None, keepdim=False, name=None):
    return _d("nanmedian", (x,), {"axis": axis if axis is None else int(axis),
                                  "keepdim": bool(keepdim)})


register_op("quantile", lambda x, *, q, axis, keepdim:
            jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return _d("quantile", (x,), {"q": q, "axis": axis if axis is None else int(axis),
                                 "keepdim": bool(keepdim)})


def bincount(x, weights=None, minlength=0, name=None):
    v = x._value if isinstance(x, Tensor) else x
    w = weights._value if isinstance(weights, Tensor) else weights
    n = max(int(v.max()) + 1 if v.size else 0, minlength)
    return Tensor._wrap(jnp.bincount(v, weights=w, length=n))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    hist, edges = np.histogramdd(v, bins=bins, range=ranges, density=density,
                                 weights=np.asarray(weights._value)
                                 if isinstance(weights, Tensor) else weights)
    return Tensor._wrap(jnp.asarray(hist)), [Tensor._wrap(jnp.asarray(e))
                                             for e in edges]
