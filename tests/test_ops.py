"""Op corpus tests via the OpTest harness (numpy forward + numeric grads)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_forward, check_grad


RNG = np.random.RandomState(42)


def _f32(*shape):
    return RNG.rand(*shape).astype(np.float32) + 0.1


class TestElementwise:
    @pytest.mark.parametrize("pfn,nfn", [
        (paddle.add, np.add), (paddle.subtract, np.subtract),
        (paddle.multiply, np.multiply), (paddle.divide, np.divide),
        (paddle.maximum, np.maximum), (paddle.minimum, np.minimum),
    ])
    def test_binary_forward(self, pfn, nfn):
        check_forward(pfn, nfn, [_f32(3, 4), _f32(3, 4)])

    @pytest.mark.parametrize("pfn", [paddle.add, paddle.multiply,
                                     paddle.subtract, paddle.divide])
    def test_binary_grad(self, pfn):
        check_grad(pfn, [_f32(2, 3), _f32(2, 3)])

    @pytest.mark.parametrize("pfn,nfn", [
        (paddle.exp, np.exp), (paddle.log, np.log), (paddle.sqrt, np.sqrt),
        (paddle.tanh, np.tanh), (paddle.sin, np.sin), (paddle.cos, np.cos),
        (paddle.abs, np.abs), (paddle.floor, np.floor), (paddle.ceil, np.ceil),
        (paddle.square, np.square),
    ])
    def test_unary_forward(self, pfn, nfn):
        check_forward(pfn, nfn, [_f32(4, 4)], rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("pfn", [paddle.exp, paddle.log, paddle.sqrt,
                                     paddle.tanh, paddle.square])
    def test_unary_grad(self, pfn):
        check_grad(pfn, [_f32(3, 3) + 0.5])

    def test_pow_scalar(self):
        check_forward(lambda x: paddle.pow(x, 3.0),
                      lambda x: np.power(x, 3.0), [_f32(3)])

    def test_clip(self):
        x = np.array([-1.0, 0.5, 2.0], np.float32)
        out = paddle.clip(paddle.to_tensor(x), 0.0, 1.0)
        np.testing.assert_allclose(out.numpy(), [0, 0.5, 1.0])

    def test_scale(self):
        out = paddle.scale(paddle.to_tensor([1.0, 2.0]), scale=2.0, bias=1.0)
        np.testing.assert_allclose(out.numpy(), [3.0, 5.0])


class TestReductions:
    @pytest.mark.parametrize("pfn,nfn", [
        (paddle.sum, np.sum), (paddle.mean, np.mean),
        (paddle.max, np.max), (paddle.min, np.min), (paddle.prod, np.prod),
    ])
    def test_forward_all(self, pfn, nfn):
        check_forward(lambda t: pfn(t), lambda a: nfn(a), [_f32(3, 4)],
                      rtol=1e-4, atol=1e-5)

    def test_axis_keepdim(self):
        x = _f32(2, 3, 4)
        out = paddle.sum(paddle.to_tensor(x), axis=[1, 2], keepdim=True)
        np.testing.assert_allclose(out.numpy(), x.sum(axis=(1, 2), keepdims=True),
                                   rtol=1e-5)

    def test_mean_grad(self):
        check_grad(lambda t: paddle.mean(t, axis=1), [_f32(3, 4)])

    def test_std_var(self):
        x = _f32(5, 5)
        np.testing.assert_allclose(paddle.std(paddle.to_tensor(x)).item(),
                                   x.std(ddof=1), rtol=1e-4)
        np.testing.assert_allclose(paddle.var(paddle.to_tensor(x)).item(),
                                   x.var(ddof=1), rtol=1e-4)

    def test_cumsum(self):
        x = _f32(3, 4)
        np.testing.assert_allclose(paddle.cumsum(paddle.to_tensor(x), axis=1).numpy(),
                                   np.cumsum(x, axis=1), rtol=1e-5)

    def test_logsumexp_grad(self):
        check_grad(lambda t: paddle.logsumexp(t, axis=1), [_f32(3, 4)])


class TestManipulation:
    def test_reshape_paddle_semantics(self):
        x = paddle.ones([2, 3, 4])
        assert paddle.reshape(x, [0, -1]).shape == [2, 12]
        assert paddle.reshape(x, [-1]).shape == [24]

    def test_concat_stack(self):
        a, b = _f32(2, 3), _f32(2, 3)
        check_forward(lambda x, y: paddle.concat([x, y], axis=0),
                      lambda x, y: np.concatenate([x, y], axis=0), [a, b])
        check_forward(lambda x, y: paddle.stack([x, y], axis=1),
                      lambda x, y: np.stack([x, y], axis=1), [a, b])

    def test_concat_grad(self):
        check_grad(lambda x, y: paddle.concat([x, y], axis=1),
                   [_f32(2, 2), _f32(2, 3)])

    def test_split_sections(self):
        x = paddle.to_tensor(_f32(7, 2))
        outs = paddle.split(x, [2, 2, 3], axis=0)
        assert [o.shape[0] for o in outs] == [2, 2, 3]

    def test_squeeze_unsqueeze(self):
        x = paddle.ones([1, 3, 1])
        assert paddle.squeeze(x).shape == [3]
        assert paddle.squeeze(x, axis=0).shape == [3, 1]
        assert paddle.unsqueeze(x, [0, 4]).shape == [1, 1, 3, 1, 1]

    def test_flatten(self):
        x = paddle.ones([2, 3, 4])
        assert paddle.flatten(x).shape == [24]
        assert paddle.flatten(x, 1, 2).shape == [2, 12]

    def test_expand_tile(self):
        x = paddle.ones([1, 3])
        assert paddle.expand(x, [4, 3]).shape == [4, 3]
        assert paddle.expand(x, [2, -1]).shape == [2, 3]
        assert paddle.tile(x, [2, 2]).shape == [2, 6]

    def test_gather_scatter(self):
        x = _f32(5, 3)
        idx = np.array([0, 3], np.int32)
        out = paddle.gather(paddle.to_tensor(x), paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), x[idx])
        upd = _f32(2, 3)
        s = paddle.scatter(paddle.to_tensor(x), paddle.to_tensor(idx),
                           paddle.to_tensor(upd))
        ref = x.copy()
        ref[idx] = upd
        np.testing.assert_allclose(s.numpy(), ref)

    def test_gather_nd(self):
        x = _f32(3, 4, 5)
        idx = np.array([[0, 1], [2, 3]], np.int32)
        out = paddle.gather_nd(paddle.to_tensor(x), paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), x[[0, 2], [1, 3]])

    def test_pad(self):
        x = _f32(2, 3)
        out = paddle.pad(paddle.to_tensor(x), [1, 1, 0, 2])
        assert out.shape == [4, 5]

    def test_take_along_axis(self):
        x = _f32(3, 4)
        idx = np.argsort(x, axis=1).astype(np.int32)
        out = paddle.take_along_axis(paddle.to_tensor(x), paddle.to_tensor(idx), 1)
        np.testing.assert_allclose(out.numpy(), np.take_along_axis(x, idx, 1))

    def test_one_hot(self):
        out = paddle.one_hot(paddle.to_tensor([0, 2]), 3)
        np.testing.assert_allclose(out.numpy(), [[1, 0, 0], [0, 0, 1]])

    def test_where(self):
        c = np.array([True, False])
        out = paddle.where(paddle.to_tensor(c), paddle.ones([2]), paddle.zeros([2]))
        np.testing.assert_allclose(out.numpy(), [1, 0])

    def test_flip_roll(self):
        x = _f32(3, 4)
        np.testing.assert_allclose(paddle.flip(paddle.to_tensor(x), [0]).numpy(),
                                   x[::-1])
        np.testing.assert_allclose(paddle.roll(paddle.to_tensor(x), 1, 0).numpy(),
                                   np.roll(x, 1, 0))


class TestLinalg:
    def test_matmul_transpose_flags(self):
        a, b = _f32(3, 4), _f32(5, 4)
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                            transpose_y=True)
        np.testing.assert_allclose(out.numpy(), a @ b.T, rtol=1e-4)

    def test_batched_matmul(self):
        a, b = _f32(2, 3, 4), _f32(2, 4, 5)
        out = paddle.bmm(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-4)

    def test_matmul_grad(self):
        check_grad(paddle.matmul, [_f32(3, 4), _f32(4, 2)], rtol=2e-2)

    def test_norm(self):
        x = _f32(3, 4)
        np.testing.assert_allclose(paddle.norm(paddle.to_tensor(x)).item(),
                                   np.linalg.norm(x), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.norm(paddle.to_tensor(x), p=1, axis=1).numpy(),
            np.abs(x).sum(axis=1), rtol=1e-5)

    def test_einsum(self):
        a, b = _f32(3, 4), _f32(4, 5)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-4)

    def test_solve_inverse(self):
        a = _f32(3, 3) + 3 * np.eye(3, dtype=np.float32)
        b = _f32(3, 2)
        out = paddle.linalg.solve(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), np.linalg.solve(a, b), rtol=1e-3,
                                   atol=1e-4)
        inv = paddle.linalg.inverse(paddle.to_tensor(a))
        np.testing.assert_allclose(inv.numpy(), np.linalg.inv(a), rtol=1e-3,
                                   atol=1e-4)


class TestSearchSort:
    def test_argmax_min(self):
        x = _f32(3, 4)
        assert paddle.argmax(paddle.to_tensor(x)).item() == x.argmax()
        np.testing.assert_array_equal(
            paddle.argmax(paddle.to_tensor(x), axis=1).numpy(), x.argmax(1))

    def test_sort_argsort(self):
        x = _f32(4, 5)
        np.testing.assert_allclose(paddle.sort(paddle.to_tensor(x), axis=1).numpy(),
                                   np.sort(x, axis=1))
        out = paddle.sort(paddle.to_tensor(x), axis=1, descending=True)
        np.testing.assert_allclose(out.numpy(), -np.sort(-x, axis=1))

    def test_topk(self):
        x = _f32(3, 10)
        vals, idx = paddle.topk(paddle.to_tensor(x), 3, axis=1)
        ref = -np.sort(-x, axis=1)[:, :3]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)

    def test_nonzero(self):
        x = np.array([[1.0, 0.0], [0.0, 2.0]])
        out = paddle.nonzero(paddle.to_tensor(x))
        np.testing.assert_array_equal(out.numpy(), [[0, 0], [1, 1]])

    def test_unique(self):
        out = paddle.unique(paddle.to_tensor([3, 1, 2, 1, 3]))
        np.testing.assert_array_equal(out.numpy(), [1, 2, 3])

    def test_searchsorted(self):
        seq = paddle.to_tensor([1.0, 3.0, 5.0])
        out = paddle.searchsorted(seq, paddle.to_tensor([2.0, 5.0]))
        np.testing.assert_array_equal(out.numpy(), [1, 2])


class TestLogic:
    def test_comparisons(self):
        a = paddle.to_tensor([1.0, 2.0])
        b = paddle.to_tensor([2.0, 2.0])
        assert paddle.equal(a, b).numpy().tolist() == [False, True]
        assert paddle.less_than(a, b).numpy().tolist() == [True, False]
        assert paddle.allclose(a, a).item()

    def test_logical(self):
        t = paddle.to_tensor([True, False])
        f = paddle.to_tensor([False, False])
        assert paddle.logical_or(t, f).numpy().tolist() == [True, False]
        assert paddle.logical_not(f).numpy().tolist() == [True, True]
        assert paddle.any(t).item()
        assert not paddle.all(t).item()


class TestRandom:
    def test_shapes_and_ranges(self):
        assert paddle.rand([3, 4]).shape == [3, 4]
        u = paddle.uniform([100], min=2.0, max=3.0)
        assert float(u.min().item()) >= 2.0 and float(u.max().item()) <= 3.0
        r = paddle.randint(0, 5, [100])
        assert int(r.max().item()) < 5
        p = paddle.randperm(10)
        assert sorted(p.numpy().tolist()) == list(range(10))

    def test_seed_determinism(self):
        paddle.seed(7)
        a = paddle.randn([4]).numpy()
        paddle.seed(7)
        b = paddle.randn([4]).numpy()
        np.testing.assert_array_equal(a, b)

    def test_bernoulli_multinomial(self):
        probs = paddle.full([1000], 0.5)
        draws = paddle.bernoulli(probs)
        assert 300 < draws.sum().item() < 700
        m = paddle.multinomial(paddle.to_tensor([0.1, 0.0, 0.9]), 5,
                               replacement=True)
        assert set(m.numpy().tolist()) <= {0, 2}


def test_nan_inf_flag():
    paddle.set_flags({"check_nan_inf": True})
    try:
        x = paddle.to_tensor([1.0, 0.0])
        with pytest.raises(FloatingPointError):
            paddle.log(paddle.to_tensor([-1.0]))
    finally:
        paddle.set_flags({"check_nan_inf": False})


def test_bitwise_operators_on_ints():
    a = paddle.to_tensor([6, 3], dtype="int32")
    b = paddle.to_tensor([3, 1], dtype="int32")
    assert (a & b).numpy().tolist() == [2, 1]
    assert (a | b).numpy().tolist() == [7, 3]
    assert str((a & b).dtype) == "int32"


def test_descending_sort_unsigned_and_bool():
    s = paddle.sort(paddle.to_tensor(np.array([0, 200, 3], np.uint8)),
                    descending=True)
    assert s.numpy().tolist() == [200, 3, 0]
    sb = paddle.sort(paddle.to_tensor([True, False]), descending=True)
    assert sb.numpy().tolist() == [True, False]


def test_round_half_away_from_zero():
    out = paddle.round(paddle.to_tensor([0.5, 1.5, 2.5, -0.5]))
    assert out.numpy().tolist() == [1.0, 2.0, 3.0, -1.0]


def test_expand_invalid_minus_one():
    with pytest.raises(ValueError):
        paddle.expand(paddle.ones([3]), [-1, 3])


def test_nan_inf_deferred_stride():
    """stride>1: flags accumulate on device, one sync per window."""
    from paddle_tpu.ops import registry
    paddle.set_flags({"check_nan_inf": True, "check_nan_inf_stride": 4})
    try:
        paddle.log(paddle.to_tensor([-1.0]))  # bad, but deferred
        paddle.exp(paddle.to_tensor([1.0]))   # fine
        assert len(registry._nan_check_ring) >= 1
        with pytest.raises(FloatingPointError, match="log"):
            # filling the window (or flushing) surfaces the offender
            registry.flush_nan_checks()
        assert registry._nan_check_ring == []
    finally:
        paddle.set_flags({"check_nan_inf": False,
                          "check_nan_inf_stride": 1})


def test_nan_inf_flush_on_disable():
    """Disabling the checker is a sync point for deferred flags."""
    paddle.set_flags({"check_nan_inf": True, "check_nan_inf_stride": 8})
    try:
        paddle.sqrt(paddle.to_tensor([-4.0]))  # deferred NaN
        with pytest.raises(FloatingPointError, match="sqrt"):
            paddle.set_flags({"check_nan_inf": False})
    finally:
        paddle.set_flags({"check_nan_inf": False,
                          "check_nan_inf_stride": 1})
