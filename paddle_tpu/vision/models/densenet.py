"""DenseNet. Parity: `python/paddle/vision/models/densenet.py`.

Dense blocks concatenate every preceding feature map — on TPU the concats
are pure layout ops XLA fuses into the following conv's input, so the
architecture maps cleanly onto the MXU without the memory-copy cost it has
in eager CUDA frameworks.
"""

from __future__ import annotations

from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, num_input_features, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(num_input_features)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(num_input_features, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.drop_rate = drop_rate

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.drop_rate > 0:
            out = nn.functional.dropout(out, p=self.drop_rate,
                                        training=self.training)
        return out


class _DenseBlock(nn.Layer):
    def __init__(self, num_layers, num_input_features, bn_size, growth_rate,
                 drop_rate):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(num_input_features + i * growth_rate, growth_rate,
                        bn_size, drop_rate)
            for i in range(num_layers)])

    def forward(self, x):
        from ...ops import manipulation as _m
        features = [x]
        for layer in self.layers:
            new = layer(_m.concat(features, axis=1)
                        if len(features) > 1 else features[0])
            features.append(new)
        return _m.concat(features, axis=1)


class _Transition(nn.Sequential):
    def __init__(self, num_input_features, num_output_features):
        super().__init__(
            nn.BatchNorm2D(num_input_features),
            nn.ReLU(),
            nn.Conv2D(num_input_features, num_output_features, 1,
                      bias_attr=False),
            nn.AvgPool2D(kernel_size=2, stride=2))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        if layers not in _CFG:
            raise ValueError(f"supported layers: {sorted(_CFG)}")
        num_init_features, growth_rate, block_config = _CFG[layers]
        self.features_stem = nn.Sequential(
            nn.Conv2D(3, num_init_features, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init_features),
            nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2, padding=1))
        blocks = []
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            blocks.append(_DenseBlock(num_layers, num_features, bn_size,
                                      growth_rate, dropout))
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                blocks.append(_Transition(num_features, num_features // 2))
                num_features //= 2
        self.blocks = nn.Sequential(*blocks)
        self.norm_final = nn.BatchNorm2D(num_features)
        self.relu = nn.ReLU()
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Linear(num_features, num_classes)

    def forward(self, x):
        x = self.features_stem(x)
        x = self.relu(self.norm_final(self.blocks(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import manipulation as _m
            x = self.classifier(_m.flatten(x, start_axis=1))
        return x


def _densenet(layers, **kwargs):
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, **kwargs)
