"""paddle.incubate.autotune — tuning-config facade.

Parity: `python/paddle/incubate/autotune.py:24` set_config (kernel /
layout / dataloader tuning).  TPU seat: XLA owns kernel autotuning; the
knobs with real effect here are the persistent compilation cache
(kernel.enable) and dataloader tuning (accepted and recorded — the
io.DataLoader picks worker counts itself on this host).
"""

from __future__ import annotations

import json
import warnings

__all__ = ["set_config"]

_config = {"kernel": {"enable": False},
           "layout": {"enable": False},
           "dataloader": {"enable": False}}


def set_config(config=None):
    """Accepts a dict or a JSON file path (the reference's contract)."""
    if config is None:
        _config["kernel"]["enable"] = True
        _config["layout"]["enable"] = True
        _config["dataloader"]["enable"] = True
    elif isinstance(config, str):
        with open(config) as f:
            set_config(json.load(f))
        return
    elif isinstance(config, dict):
        for k, v in config.items():
            if k not in _config:
                warnings.warn(f"autotune.set_config: unknown field {k!r}")
                continue
            _config[k].update(v)
    if _config["kernel"]["enable"]:
        # XLA's kernel autotune runs unconditionally; the persistent
        # compile cache is the knob that saves its results across runs
        import jax
        try:
            import os
            d = os.path.join(os.path.expanduser("~"), ".paddle_tpu_cache")
            os.makedirs(d, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", d)
        except Exception:  # noqa: BLE001 - cache dir is best-effort
            pass


def get_config():
    return {k: dict(v) for k, v in _config.items()}
