"""paddle.distributed.rpc over the TCPStore control plane.

Parity: `python/paddle/distributed/rpc/rpc.py` — named workers,
rpc_sync/rpc_async, exception propagation, worker info registry.
Workers are simulated as two in-process agents over one store.
"""

import numpy as np

from paddle_tpu.distributed.rpc import _RpcAgent, WorkerInfo
from paddle_tpu.distributed.store import TCPStore


def _pair():
    import threading
    store = TCPStore(is_master=True, world_size=1)
    a = _RpcAgent("alice", 0, 2, store)
    b = _RpcAgent("bob", 1, 2, store)
    # register() blocks until every rank has published its info — run both
    # concurrently, as the two real worker processes would
    t = threading.Thread(target=a.register)
    t.start()
    b.register()
    t.join(timeout=30)
    return a, b


def _add(x, y):
    return x + y


def _boom():
    raise ValueError("remote boom")


def test_rpc_sync_roundtrip_and_registry():
    a, b = _pair()
    try:
        assert a.workers["bob"] == WorkerInfo("bob", 1)
        fut = a.invoke("bob", _add, (2, 3), {}, timeout=30)
        assert fut.result(30) == 5
        # reverse direction
        fut = b.invoke("alice", _add, (np.arange(3), 10), {}, timeout=30)
        np.testing.assert_array_equal(fut.result(30), [10, 11, 12])
    finally:
        a.shutdown()
        b.shutdown()


def test_rpc_async_many_and_exception():
    a, b = _pair()
    try:
        futs = [a.invoke("bob", _add, (i, i), {}, timeout=30)
                for i in range(8)]
        assert [f.result(30) for f in futs] == [0, 2, 4, 6, 8, 10, 12, 14]
        err = a.invoke("bob", _boom, (), {}, timeout=30)
        exc = err.exception(30)
        assert isinstance(exc, ValueError) and "remote boom" in str(exc)
    finally:
        a.shutdown()
        b.shutdown()
