"""Llama-2 model family (BASELINE config 5: Llama-2 7B semi-auto parallel).

Architecture: RMSNorm pre-norm, SwiGLU MLP, rotary embeddings, no biases —
matching the reference ecosystem's `semi_auto_llama.py`
(`test/auto_parallel/hybrid_strategy/semi_auto_llama.py`).  Attention runs
through the SDPA/Pallas path; RoPE through the fused rope op."""

from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..incubate.nn.functional import fused_rotary_position_embedding, swiglu
from ..nn import functional as F
from .generation import GenerationMixin
from ..ops import creation, manipulation as _m

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama_tiny",
           "llama2_7b", "llama2_13b"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 0  # 0 -> same as num_heads (MHA); else GQA
    intermediate_size: int = 11008
    max_seq_len: int = 4096
    rms_eps: float = 1e-6
    rope_base: float = 10000.0
    use_recompute: bool = False
    tensor_parallel: bool = False

    def __post_init__(self):
        if self.num_kv_heads == 0:
            self.num_kv_heads = self.num_heads


class LlamaAttention(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.head_dim = cfg.hidden_size // cfg.num_heads
        h, kvh = cfg.num_heads, cfg.num_kv_heads
        if cfg.tensor_parallel:
            from ..distributed.fleet import (ColumnParallelLinear,
                                             RowParallelLinear)
            mk = lambda i, o: ColumnParallelLinear(i, o, has_bias=False,
                                                   gather_output=False)
            self.q_proj = mk(cfg.hidden_size, h * self.head_dim)
            self.k_proj = mk(cfg.hidden_size, kvh * self.head_dim)
            self.v_proj = mk(cfg.hidden_size, kvh * self.head_dim)
            self.o_proj = RowParallelLinear(h * self.head_dim, cfg.hidden_size,
                                            has_bias=False,
                                            input_is_parallel=True)
        else:
            self.q_proj = nn.Linear(cfg.hidden_size, h * self.head_dim,
                                    bias_attr=False)
            self.k_proj = nn.Linear(cfg.hidden_size, kvh * self.head_dim,
                                    bias_attr=False)
            self.v_proj = nn.Linear(cfg.hidden_size, kvh * self.head_dim,
                                    bias_attr=False)
            self.o_proj = nn.Linear(h * self.head_dim, cfg.hidden_size,
                                    bias_attr=False)

    def forward(self, x, kv_cache=None, pos_offset=None):
        cfg = self.cfg
        b, s = x.shape[0], x.shape[1]
        q = _m.reshape(self.q_proj(x), [b, s, cfg.num_heads, self.head_dim])
        k = _m.reshape(self.k_proj(x), [b, s, cfg.num_kv_heads, self.head_dim])
        v = _m.reshape(self.v_proj(x), [b, s, cfg.num_kv_heads, self.head_dim])
        if pos_offset is not None:
            offset = pos_offset
        else:
            offset = kv_cache[0].shape[1] if kv_cache is not None else 0
        import numpy as _np
        if isinstance(offset, int):
            pos = _np.arange(offset, offset + s) if offset else None
        else:  # traced offset (compiled decode loop): keep shapes static
            import jax.numpy as _jnp
            pos = _jnp.arange(s) + offset
        q, k, _ = fused_rotary_position_embedding(
            q, k, None, position_ids=pos, use_neox_rotary_style=True,
            rotary_emb_base=cfg.rope_base)
        if kv_cache is not None and not isinstance(kv_cache, tuple):
            # paged/static cache (non-tuple): both attend one q head per
            # cached kv head, so GQA caches the repeated heads
            if cfg.num_kv_heads != cfg.num_heads:
                rep = cfg.num_heads // cfg.num_kv_heads
                k = _m.repeat_interleave(k, rep, axis=2)
                v = _m.repeat_interleave(v, rep, axis=2)
            from .kv_cache import PagedKVCache, StaticKVCache
            if isinstance(kv_cache, (StaticKVCache, PagedKVCache)):
                from ..framework.tensor import Tensor as _T
                new_cache, out = kv_cache.update_and_attend(
                    q._value, k._value, v._value)
                out_t = _T._wrap(out.reshape(
                    b, s, cfg.num_heads * self.head_dim))
                return self.o_proj(out_t), new_cache
            return self._paged_forward(q, k, v, kv_cache, b, s)
        new_cache = None
        if kv_cache is not None:
            pk, pv = kv_cache
            k = _m.concat([pk, k], axis=1)
            v = _m.concat([pv, v], axis=1)
            new_cache = (k, v)
        # GQA (num_kv_heads < num_heads) is resolved inside the attention
        # functional: the Pallas kernel maps head groups via index maps
        # (repeated K/V never reach HBM), the XLA fallback repeats there
        k_len = k.shape[1]
        if k_len == s:
            mask, causal = None, True
        elif s == 1:
            mask, causal = None, False  # decode token sees all cache
        else:
            # chunked prefill: offset-aware causal mask
            import jax.numpy as _jnp
            qpos = _jnp.arange(k_len - s, k_len)[:, None]
            kpos = _jnp.arange(k_len)[None, :]
            from ..framework.tensor import Tensor as _T
            mask, causal = _T._wrap(qpos >= kpos), False
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=mask,
                                             is_causal=causal,
                                             training=self.training)
        out = _m.reshape(out, [b, s, cfg.num_heads * self.head_dim])
        out = self.o_proj(out)
        return out if new_cache is None else (out, new_cache)

    def _paged_forward(self, q, k, v, cache, b, s):
        """Decode/prefill against a paged block cache (see
        `models/gpt.py:_paged_forward`; same Pallas kernel)."""
        from ..framework.tensor import Tensor as _T
        cfg = self.cfg
        if s == 1:
            cache.append(k._value[:, 0], v._value[:, 0])
            out = cache.attend(q._value[:, 0])
            out_t = _T._wrap(out[:, None].reshape(
                b, 1, cfg.num_heads * self.head_dim))
        else:
            if cache._lens and cache._lens[0] != 0:
                raise NotImplementedError(
                    "chunked prefill against a paged cache; prefill in one "
                    "chunk or use cache_impl='dense'")
            cache.append_prefill(k._value, v._value)
            dense = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, training=False)
            out_t = _m.reshape(dense,
                               [b, s, cfg.num_heads * self.head_dim])
        return self.o_proj(out_t), cache


class LlamaMLP(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        if cfg.tensor_parallel:
            from ..distributed.fleet import (ColumnParallelLinear,
                                             RowParallelLinear)
            self.gate_proj = ColumnParallelLinear(cfg.hidden_size,
                                                  cfg.intermediate_size,
                                                  has_bias=False,
                                                  gather_output=False)
            self.up_proj = ColumnParallelLinear(cfg.hidden_size,
                                                cfg.intermediate_size,
                                                has_bias=False,
                                                gather_output=False)
            self.down_proj = RowParallelLinear(cfg.intermediate_size,
                                               cfg.hidden_size,
                                               has_bias=False,
                                               input_is_parallel=True)
        else:
            self.gate_proj = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                                       bias_attr=False)
            self.up_proj = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                                     bias_attr=False)
            self.down_proj = nn.Linear(cfg.intermediate_size, cfg.hidden_size,
                                       bias_attr=False)

    def forward(self, x):
        return self.down_proj(swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaBlock(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size, cfg.rms_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   cfg.rms_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x, kv_cache=None, pos_offset=None):
        if kv_cache is None:
            x = x + self.self_attn(self.input_layernorm(x))
        else:
            a, new_cache = self.self_attn(self.input_layernorm(x), kv_cache,
                                          pos_offset)
            x = x + a
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x if kv_cache is None else (x, new_cache)


class LlamaModel(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.tensor_parallel:
            from ..distributed.fleet import VocabParallelEmbedding
            self.embed_tokens = VocabParallelEmbedding(cfg.vocab_size,
                                                       cfg.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.layers = nn.LayerList([LlamaBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, cfg.rms_eps)

    def forward(self, input_ids, kv_caches=None, pos_offset=None):
        x = self.embed_tokens(input_ids)
        if kv_caches is not None:
            new_caches = []
            for layer, cache in zip(self.layers, kv_caches):
                x, nc = layer(x, cache, pos_offset)
                new_caches.append(nc)
            return self.norm(x), new_caches
        if self.cfg.use_recompute and self.training:
            from ..distributed.fleet import recompute
            for layer in self.layers:
                x = recompute(layer, x)
        else:
            for layer in self.layers:
                x = layer(x)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids):
        return self.lm_head(self.model(input_ids))

    def init_caches(self, batch_size, cache_impl: str = "dense",
                    block_size: int = None, max_context=None):
        import jax.numpy as jnp
        from ..framework.tensor import Tensor as _T
        cfg = self.cfg
        hd = cfg.hidden_size // cfg.num_heads
        dtype = self.model.embed_tokens.weight._value.dtype
        if cache_impl == "paged" and max_context is not None:
            # compiled serving path (see gpt.py): pool sized by the actual
            # generation context; caches hold GQA-repeated heads
            from .kv_cache import PagedKVCache
            return [PagedKVCache(batch_size, max_context, cfg.num_heads,
                                 hd, dtype, block_size=block_size or 64)
                    for _ in range(cfg.num_layers)]
        if cache_impl == "paged":
            block_size = block_size or 16
            from ..ops.pallas_paged import BlockKVCache
            max_blocks = (cfg.max_seq_len + block_size - 1) // block_size
            return [BlockKVCache(
                num_blocks=batch_size * max_blocks + 1,
                block_size=block_size, num_heads=cfg.num_heads,
                head_dim=hd, batch=batch_size,
                max_blocks_per_seq=max_blocks, dtype=dtype)
                for _ in range(cfg.num_layers)]
        if cache_impl == "static":
            # like the paged cache, static caches hold the GQA-repeated
            # heads (attention there is one q head per cached kv head)
            from .kv_cache import StaticKVCache
            return [StaticKVCache(batch_size, cfg.max_seq_len,
                                  cfg.num_heads, hd, dtype)
                    for _ in range(cfg.num_layers)]
        empty = lambda: _T._wrap(jnp.zeros(
            (batch_size, 0, cfg.num_kv_heads, hd), dtype))
        return [(empty(), empty()) for _ in range(cfg.num_layers)]

    def forward_with_cache(self, input_ids, caches, pos_offset=0):
        h, new_caches = self.model(input_ids, kv_caches=caches,
                                   pos_offset=pos_offset)
        return self.lm_head(h), new_caches

    def compute_loss(self, input_ids, labels):
        logits = self(input_ids)
        return F.cross_entropy(
            _m.reshape(logits, [-1, self.cfg.vocab_size]),
            _m.reshape(labels, [-1]))

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def flops_per_token(self, seq_len=None) -> float:
        from ..observability.flops import training_flops_per_token
        return training_flops_per_token(
            self.num_params(), self.cfg.num_layers, self.cfg.hidden_size,
            seq_len or self.cfg.max_seq_len)


def llama_tiny(**kw):
    return LlamaConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                       num_heads=4, intermediate_size=384, max_seq_len=256,
                       **kw)


def llama2_7b(**kw):
    return LlamaConfig(hidden_size=4096, num_layers=32, num_heads=32,
                       intermediate_size=11008, max_seq_len=4096, **kw)


def llama2_13b(**kw):
    return LlamaConfig(hidden_size=5120, num_layers=40, num_heads=40,
                       intermediate_size=13824, max_seq_len=4096, **kw)
