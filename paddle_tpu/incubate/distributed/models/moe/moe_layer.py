"""MoE layer with expert parallelism.

Parity: `python/paddle/incubate/distributed/models/moe/moe_layer.py:263`
(MoELayer), `:99/:149` (MoEScatter/MoEGather — replaced by dense einsum
dispatch), `utils.py` (prepare_forward — replaced by the gate's fixed
capacity).

TPU-native: the reference scatters tokens with index ops and moves them
between ranks with an explicit NCCL all-to-all (`global_scatter/gather`).
Here dispatch/combine are einsums over a fixed-capacity buffer
(T,E,C)x(T,M)->(E,C,M); experts run as one batched einsum over stacked
weights (E,M,H)/(E,H,M) so the MXU sees large matmuls; when the stacked
expert dim is sharded over an `ep` mesh axis, GSPMD lowers the dispatch
einsum to the same all-to-all the reference codes by hand — and it rides
ICI inside a jit program instead of going through host NCCL calls.

Fused dispatch (ISSUE 18, default on): the dense dispatch/combine
einsums contract against (T, E, C) one-hot tensors — ``T*E*C*M`` FLOPs
for what is a gather of ``T*k`` rows.  With ``FLAGS_moe_fused_dispatch``
the layer takes the gate's index-form routing (`forward_indices`) and
runs the one-pass Pallas dispatch/combine kernels of
`ops/pallas_moe.py` instead; the dense einsum path stays as the oracle
and the fallback when pallas is unavailable.  The flag is snapshotted
at layer construction (R004: no flag reads inside traced fns).
:func:`audit_dispatch` lowers the active data plane into the X-ray
kernel-coverage ledger — the MoE analogue of the serving warmup audit.
"""

from __future__ import annotations

import math
from typing import Optional

import paddle_tpu as paddle
from paddle_tpu.nn.layer.layers import Layer
import paddle_tpu.nn.functional as F
from paddle_tpu import flags as _flags
from paddle_tpu.ops import pallas_kernels as _pk

from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["ExpertMLP", "MoELayer", "audit_dispatch"]


class ExpertMLP(Layer):
    """E parallel two-layer MLPs with stacked weights.

    Weights are (E, d_model, d_hidden) / (E, d_hidden, d_model) so the whole
    expert computation is two einsums; shard dim 0 over the `ep` mesh axis
    for expert parallelism.
    """

    def __init__(self, num_expert: int, d_model: int, d_hidden: int,
                 activation: str = "gelu"):
        super().__init__()
        self.num_expert = num_expert
        self.d_model = d_model
        self.d_hidden = d_hidden
        scale1 = 1.0 / math.sqrt(d_model)
        scale2 = 1.0 / math.sqrt(d_hidden)
        self.w1 = self.create_parameter(
            [num_expert, d_model, d_hidden],
            default_initializer=paddle.nn.initializer.Uniform(-scale1, scale1))
        self.b1 = self.create_parameter(
            [num_expert, 1, d_hidden],
            default_initializer=paddle.nn.initializer.Constant(0.0))
        self.w2 = self.create_parameter(
            [num_expert, d_hidden, d_model],
            default_initializer=paddle.nn.initializer.Uniform(-scale2, scale2))
        self.b2 = self.create_parameter(
            [num_expert, 1, d_model],
            default_initializer=paddle.nn.initializer.Constant(0.0))
        self.act = getattr(F, activation)

    def forward(self, x):
        """x: (E, C, d_model) -> (E, C, d_model), batched over experts."""
        h = paddle.einsum("ecm,emh->ech", x, self.w1) + self.b1
        h = self.act(h)
        return paddle.einsum("ech,ehm->ecm", h, self.w2) + self.b2


class MoELayer(Layer):
    """Mixture-of-experts layer: gate -> dispatch -> experts -> combine.

    Parity: `moe_layer.py:263`.  `gate` may be a BaseGate instance or one of
    the strings "naive"/"switch"/"gshard"; `experts` may be an ExpertMLP
    (recommended, shardable) or a list of per-token Layers applied via
    stacking is NOT supported — build an ExpertMLP instead (the reference's
    per-expert Layer list maps to stacked weights on TPU).

    After each forward the gate's aux loss is available as `self.l_aux`
    (add it to the training loss, as the reference's MoELayer callers do).
    """

    def __init__(self, d_model: int, experts: Optional[ExpertMLP] = None,
                 gate: "BaseGate | str" = "gshard", num_expert: int = None,
                 d_hidden: int = None, top_k: int = 2,
                 capacity_factor: Optional[float] = None, moe_group=None,
                 mp_group=None, **gate_kwargs):
        super().__init__()
        if experts is None:
            assert num_expert and d_hidden, \
                "give experts= or (num_expert=, d_hidden=)"
            experts = ExpertMLP(num_expert, d_model, d_hidden)
        self.experts = experts
        E = experts.num_expert
        if isinstance(gate, str):
            cf = 1.25 if capacity_factor is None else capacity_factor
            if gate == "naive":
                gate = NaiveGate(d_model, E, top_k=top_k,
                                 capacity_factor=cf, **gate_kwargs)
            elif gate == "switch":
                gate = SwitchGate(d_model, E, capacity_factor=cf,
                                  **gate_kwargs)
            elif gate == "gshard":
                if top_k != 2:
                    raise ValueError("gshard gate routes top-2; use "
                                     "gate='naive' for other top_k")
                if "capacity" not in gate_kwargs and \
                        capacity_factor is not None:
                    # translate tokens/(E*k) factor to GShard's tokens/E tuple
                    gate_kwargs["capacity"] = (2 * capacity_factor,
                                               2 * capacity_factor)
                gate = GShardGate(d_model, E, **gate_kwargs)
            else:
                raise ValueError(f"unknown gate {gate!r}")
        self.gate = gate
        self.l_aux = None
        # snapshot (R004): the fused data plane is chosen at construction,
        # never inside a traced forward
        self._fused = (bool(_flags.get_flag("moe_fused_dispatch"))
                       and _pk.moe_fused_available()
                       and hasattr(self.gate, "forward_indices"))

    def forward(self, x):
        """x: (..., d_model); routing flattens all leading dims to tokens."""
        orig_shape = x.shape
        d_model = orig_shape[-1]
        xt = paddle.reshape(x, [-1, d_model])                  # (T, M)
        if self._fused:
            out = self._forward_fused(xt)
        else:
            combine, dispatch, aux = self.gate(xt)             # (T,E,C) x2
            self.l_aux = aux
            expert_in = paddle.einsum("tec,tm->ecm", dispatch, xt)
            expert_out = self.experts(expert_in)               # (E, C, M)
            out = paddle.einsum("tec,ecm->tm", combine, expert_out)
        return paddle.reshape(out, orig_shape)

    def _forward_fused(self, xt):
        """One-pass routing: the gate's index-form decision drives the
        Pallas dispatch/combine kernels — no (T, E, C) tensors."""
        eid, slot, keep, w, cap, aux = self.gate.forward_indices(xt)
        self.l_aux = aux
        E = self.gate.tot_expert
        flat, inv = _pk.moe_routing_indices(eid, slot, keep, E, cap)
        rows = _pk.moe_dispatch(xt, inv)                       # (E*C, M)
        expert_in = paddle.reshape(rows, [E, cap, xt.shape[1]])
        expert_out = self.experts(expert_in)                   # (E, C, M)
        return _pk.moe_combine(
            paddle.reshape(expert_out, [E * cap, xt.shape[1]]), w, flat)


def audit_dispatch(layer: MoELayer, num_tokens: int = 64):
    """Register + audit the layer's dispatch/combine program in the
    X-ray kernel-coverage ledger (`xray.kernel_coverage`), the MoE
    analogue of the serving warmup audit: lower a jit of the ACTIVE
    data plane — fused kernels or dense einsums, per the layer's
    snapshot — over abstract (num_tokens, d_model) routing shapes,
    capturing trace-time kernel claims.  Returns the audit row's
    program key."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.observability import xray as _xray
    from paddle_tpu.ops import pallas_moe as _pm
    from .gate import capacity as _capacity

    gate = layer.gate
    E = gate.tot_expert
    k = gate.top_k
    M = layer.experts.d_model
    T = int(num_tokens)
    cap = _capacity(T, E, k, getattr(gate, "capacity_factor", 1.25),
                    getattr(gate, "min_capacity", 4))
    fused = layer._fused

    if fused:
        def prog(x, inv, w, flat):
            rows = _pm.moe_dispatch(x, inv)
            return _pm.moe_combine(rows, w, flat)
        shapes = (jax.ShapeDtypeStruct((T, M), jnp.float32),
                  jax.ShapeDtypeStruct((E * cap,), jnp.int32),
                  jax.ShapeDtypeStruct((T, k), jnp.float32),
                  jax.ShapeDtypeStruct((T, k), jnp.int32))
    else:
        def prog(x, dispatch, combine):
            expert_in = jnp.einsum("tec,tm->ecm", dispatch, x)
            return jnp.einsum("tec,ecm->tm", combine, expert_in)
        shapes = (jax.ShapeDtypeStruct((T, M), jnp.float32),
                  jax.ShapeDtypeStruct((T, E, cap), jnp.float32),
                  jax.ShapeDtypeStruct((T, E, cap), jnp.float32))

    entry = _xray.register(
        "moe.dispatch", (("T", T), ("E", E), ("C", cap), ("M", M),
                         ("k", k), ("fused", fused)))
    with _xray.capture_kernel_claims() as claims:
        lowered = jax.jit(prog).lower(*shapes)
    _xray.attach_lowered(entry, lowered, claims)
    return entry.key
