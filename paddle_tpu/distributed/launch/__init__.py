"""Distributed launcher.  Parity: `python/paddle/distributed/launch/`."""

from .main import CollectiveController, launch, parse_args  # noqa: F401
