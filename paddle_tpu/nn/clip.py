"""Gradient clipping. Parity: `python/paddle/nn/clip.py`
(ClipGradByGlobalNorm is what HybridParallelOptimizer composes across mesh
axes — see distributed/fleet)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm", "clip_grad_norm_"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor._wrap(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._value)))
            scale = jnp.where(norm > self.clip_norm, self.clip_norm /
                              jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor._wrap(g._value * scale)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name
        # hook used by hybrid-parallel: sums the squared-norm across mesh
        # groups before the scale is computed (fleet sets this)
        self._global_norm_reduce_fn = None

    def _compute_global_sq_norm(self, params_grads):
        sq = None
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            s = jnp.sum(jnp.square(g._value.astype(jnp.float32)))
            sq = s if sq is None else sq + s
        return sq

    def __call__(self, params_grads):
        sq = self._compute_global_sq_norm(params_grads)
        if sq is None:
            return params_grads
        if self._global_norm_reduce_fn is not None:
            sq = self._global_norm_reduce_fn(sq)
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor._wrap((g._value.astype(jnp.float32) * scale)
                                        .astype(g._value.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.power(jnp.abs(g._value), norm_type))
                              for g in grads), 1.0 / norm_type)
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._value = p.grad._value * clip_coef
    return Tensor._wrap(total)
