"""Tape-free define-by-run autograd engine.

Same design as the reference's eager engine (`fluid/eager/backward.cc:105`
RunBackward, in-degree map at `backward.cc:23`, `fluid/eager/grad_node_info.h:197`
GradNodeBase / `:53` Edge, grad accumulation `fluid/eager/accumulation/`):

* every differentiable op creates one :class:`OpGradNode` holding a VJP
  closure (by default the one returned by ``jax.vjp`` over the op's forward
  function — XLA residuals instead of Paddle's TensorWrapper saves);
* nodes are linked by :class:`Edge` (producer node, output slot);
* leaves get a :class:`GradAccumulationNode` that writes ``tensor.grad``;
* ``backward()`` seeds output grads, BFS-counts in-degrees over the edge
  graph, then walks a ready queue accumulating per-(node, slot) grads.

Grads flow as raw jax Arrays inside the engine; they are wrapped into Tensors
only when stored on leaves or handed to user hooks.
"""

from __future__ import annotations

import weakref
from collections import defaultdict, deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["Edge", "GradNode", "OpGradNode", "GradAccumulationNode", "run_backward"]


class Edge:
    """Connects one input slot of a consumer node to (producer node, out slot)."""

    __slots__ = ("node", "slot")

    def __init__(self, node: "GradNode", slot: int):
        self.node = node
        self.slot = slot


class GradNode:
    """Base grad node: maps output-cotangents -> input-cotangents."""

    op_name: str = "unknown"
    # PyLayer-style nodes take/return Tensors (user-facing backward fns);
    # plain nodes flow raw jax arrays.
    wants_tensors: bool = False

    def __init__(self, num_outputs: int):
        self.num_outputs = num_outputs
        # out_meta[i] = (shape, dtype) for constructing zero cotangents of
        # outputs that received no gradient (multi-output ops).
        self.out_meta: List[Optional[Tuple[Tuple[int, ...], Any]]] = [None] * num_outputs
        self.next_edges: List[Optional[Edge]] = []
        # user hooks on this node's *outputs'* grads (tensor.register_hook).
        self.grad_hooks: List[List[Callable]] = [[] for _ in range(num_outputs)]

    def apply(self, out_grads: List[Any]) -> List[Optional[Any]]:
        raise NotImplementedError

    def release(self):
        """Drop saved residuals (retain_graph=False path)."""


class OpGradNode(GradNode):
    """Grad node for a registered op; holds the vjp closure + static attrs.

    ``primal_vals``/``make_vjp`` retain the forward inputs and a way to
    re-linearize at traced primals — the role of the reference's
    TensorWrapper saves (`fluid/eager/tensor_wrapper.h`), needed so
    ``create_graph=True`` can differentiate the backward w.r.t. the
    primals (jax.vjp closures treat residuals as constants)."""

    __slots__ = ("vjp_fn", "input_treedef", "op_name", "tuple_out",
                 "primal_vals", "make_vjp")

    def __init__(self, op_name: str, num_outputs: int, vjp_fn: Callable,
                 tuple_out: bool = False, primal_vals=None, make_vjp=None):
        super().__init__(num_outputs)
        self.op_name = op_name
        self.vjp_fn = vjp_fn
        # a fwd returning a 1-tuple still needs a tuple cotangent
        self.tuple_out = tuple_out or num_outputs > 1
        self.primal_vals = primal_vals
        self.make_vjp = make_vjp

    def apply(self, out_grads: List[Any]) -> List[Optional[Any]]:
        if self.vjp_fn is None:
            raise RuntimeError(
                f"Grad node for op '{self.op_name}' was already released. "
                "Call backward(retain_graph=True) to backprop twice.")
        cot = tuple(out_grads) if self.tuple_out else out_grads[0]
        in_grads = self.vjp_fn(cot)
        out: List[Optional[Any]] = []
        for g in in_grads:
            out.append(_drop_float0(g))
        return out

    def release(self):
        self.vjp_fn = None
        self.primal_vals = None
        self.make_vjp = None


def _drop_float0(g):
    """jax returns float0 cotangents for integer/bool inputs — treat as None."""
    if g is None:
        return None
    if isinstance(g, (list, tuple)):
        return type(g)(_drop_float0(x) for x in g)
    dt = getattr(g, "dtype", None)
    if dt is not None and dt == jax.dtypes.float0:
        return None
    return g


class GradAccumulationNode(GradNode):
    """Leaf sink: accumulates the cotangent into ``tensor.grad``.

    Mirrors `fluid/eager/accumulation/accumulation_node.h`.  Holds a weakref so
    dead leaves don't keep memory alive; also carries reducer hooks used by
    DataParallel (`fluid/distributed/collective/reducer.h:88`).
    """

    op_name = "grad_accumulation"

    def __init__(self, tensor):
        super().__init__(1)
        self._ref = weakref.ref(tensor)
        self.reducer_hooks: List[Callable] = []

    def apply(self, out_grads: List[Any]) -> List[Optional[Any]]:
        t = self._ref()
        g = out_grads[0]
        if t is not None and g is not None:
            t._accumulate_grad(g)
            for hook in self.reducer_hooks:
                hook(t)
        return []


def _zeros_cotangent(meta):
    """Zero cotangent for an output that received no gradient.

    Integer/bool (and float0-typed) outputs take float0 cotangents
    (jax.vjp's convention for non-differentiable values)."""
    shape, dtype = meta
    if dtype == jax.dtypes.float0 or jnp.issubdtype(dtype, jnp.integer) \
            or dtype == jnp.bool_:
        import numpy as _np
        return _np.zeros(shape, jax.dtypes.float0)
    return jnp.zeros(shape, dtype)


def _unwrap(g):
    from .tensor import Tensor
    return g._value if isinstance(g, Tensor) else g


def _wrap_grad(g, create_graph: bool):
    """Tensor-ify a cotangent for Tensor-flowing modes."""
    from .tensor import Tensor
    if g is None or isinstance(g, Tensor):
        return g
    dt = getattr(g, "dtype", None)
    if dt is not None and dt == jax.dtypes.float0:
        return None
    return Tensor._wrap(g, stop_gradient=not create_graph)


def _dispatch_vjp(node: "OpGradNode", out_grads: List[Any]):
    """create_graph mode: re-linearize the op at its primals as a function
    of (primals, cotangents) so the produced gradients carry a tape that
    reaches both — the role of the reference's generated higher-order
    GradNodes (`fluid/eager/api/generated/.../backwards/`, `fluid/prim`
    double-grad composites)."""
    from .tensor import Tensor

    if node.make_vjp is None or node.primal_vals is None:
        raise RuntimeError(
            f"create_graph through '{node.op_name}' requires its primal "
            "saves; the node was released (use retain_graph=True) or the "
            "op does not retain primals")

    n_in = len(node.primal_vals)
    # float0 cotangents (non-differentiable output slots) stay raw arrays —
    # they can't be Tensors and take no edges
    cot_items = []
    for g in out_grads:
        if isinstance(g, Tensor) or \
                getattr(g, "dtype", None) == jax.dtypes.float0:
            cot_items.append(g)
        else:
            cot_items.append(_wrap_grad(g, True))

    def combined(*all_vals):
        vals, cots = all_vals[:n_in], all_vals[n_in:]
        _, vjp = node.make_vjp(list(vals))
        cot = tuple(cots) if node.tuple_out else cots[0]
        return tuple(vjp(cot))

    cot_vals = [t._value if isinstance(t, Tensor) else t for t in cot_items]
    new_outs, new_vjp = jax.vjp(combined, *node.primal_vals, *cot_vals)

    new_node = OpGradNode(
        f"grad[{node.op_name}]", len(new_outs), new_vjp, tuple_out=True,
        primal_vals=list(node.primal_vals) + cot_vals,
        make_vjp=lambda vals: jax.vjp(combined, *vals))
    edges = list(node.next_edges)
    for t in cot_items:
        if not isinstance(t, Tensor) or t.stop_gradient:
            edges.append(None)
        elif t._grad_node is not None:
            edges.append(Edge(t._grad_node, t._output_slot))
        else:
            edges.append(Edge(t._get_accum_node(), 0))
    new_node.next_edges = edges

    wrapped: List[Optional[Any]] = []
    for i, o in enumerate(new_outs):
        # record meta for every slot (incl. float0) so a second backward
        # can materialize structure-matching zero cotangents
        new_node.out_meta[i] = (tuple(o.shape), o.dtype)
        if getattr(o, "dtype", None) == jax.dtypes.float0:
            wrapped.append(None)
            continue
        w = Tensor._wrap(o, stop_gradient=False)
        w._grad_node = new_node
        w._output_slot = i
        wrapped.append(w)
    return wrapped


def run_backward(tensors: Sequence, grad_tensors: Sequence[Optional[Any]],
                 retain_graph: bool = False, create_graph: bool = False,
                 capture: Optional[dict] = None,
                 accumulate: bool = True) -> Optional[dict]:
    """The engine loop — reference: egr::RunBackward (`fluid/eager/backward.cc:105`).

    capture: {(id(node), slot): key} — record the fully-accumulated
    cotangent arriving at that (node, slot) into the returned dict (the
    mechanism behind ``paddle.grad``; reference `general_grad.h`).
    create_graph: flow cotangents as Tensors and apply each vjp as a
    dispatched op so gradients themselves are differentiable.
    accumulate: write leaf ``.grad`` (False for ``paddle.grad`` /
    only_inputs semantics).
    """
    captured: dict = {}
    # 1. Seed output grads per (node, slot).
    pending: dict = defaultdict(dict)  # node -> {slot: grad}
    roots: List[GradNode] = []
    if create_graph:
        grad_tensors = [_wrap_grad(g, True) for g in grad_tensors]
    for t, g in zip(tensors, grad_tensors):
        node, slot = t._grad_node, t._output_slot
        if node is None:
            if capture is not None and not t.stop_gradient:
                # grad() on a leaf output: gradient is the seed itself
                accum = t._get_accum_node()
                key = capture.get((id(accum), 0))
                if key is not None:
                    captured[key] = g
            if accumulate and not t.stop_gradient:
                t._accumulate_grad(_unwrap(g))
            continue
        slots = pending[node]
        slots[slot] = g if slot not in slots else slots[slot] + g
        if node not in roots:
            roots.append(node)

    if not roots:
        return captured if capture is not None else None

    # 2. In-degree map via BFS over edges (`backward.cc:23` getInDegreeMap).
    indeg: dict = defaultdict(int)
    visited = set()
    queue = deque(roots)
    visited.update(id(n) for n in roots)
    nodes_by_id = {id(n): n for n in roots}
    parents: dict = defaultdict(list)  # child id -> parent ids
    while queue:
        node = queue.popleft()
        for edge in node.next_edges:
            if edge is None:
                continue
            indeg[id(edge.node)] += 1
            parents[id(edge.node)].append(id(node))
            if id(edge.node) not in visited:
                visited.add(id(edge.node))
                nodes_by_id[id(edge.node)] = edge.node
                queue.append(edge.node)

    # 2b. Prune for paddle.grad: only nodes on a path from the outputs to a
    # requested input do real work (reference `general_grad.h` subgraph
    # selection); the rest just forward None to unblock dependencies.
    useful = None
    if capture is not None and not accumulate:
        useful = set()
        upq = deque(nid for nid, _ in capture.keys() if nid in visited
                    or nid in parents)
        useful.update(upq)
        while upq:
            nid = upq.popleft()
            for pid in parents.get(nid, ()):
                if pid not in useful:
                    useful.add(pid)
                    upq.append(pid)

    # 3. Ready-queue walk.
    ready = deque(n for n in roots if indeg[id(n)] == 0)
    processed = set()
    while ready:
        node = ready.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))

        slot_grads = pending.pop(node, {})
        if useful is not None and id(node) not in useful:
            in_grads = [None] * len(node.next_edges)
            if not retain_graph:
                node.release()
        elif not slot_grads and not isinstance(node, GradAccumulationNode):
            # No real gradient reached this node (e.g. only float0 paths):
            # propagate None but still unblock downstream nodes.
            in_grads = [None] * len(node.next_edges)
        else:
            out_grads: List[Any] = []
            for i in range(node.num_outputs):
                g = slot_grads.get(i)
                if g is None and node.out_meta[i] is not None and not isinstance(
                        node, GradAccumulationNode):
                    g = _zeros_cotangent(node.out_meta[i])
                    if create_graph:
                        g = _wrap_grad(g, True)
                for hook in node.grad_hooks[i]:
                    res = hook(g)
                    if res is not None:
                        g = res
                # AMP: a consumer computing in fp32 sends fp32 cotangents to a
                # low-precision producer — cast to the node's output dtype
                meta = node.out_meta[i]
                gd = getattr(g, "dtype", None)
                if g is not None and meta is not None and gd is not None \
                        and gd != meta[1] and \
                        jnp.issubdtype(meta[1], jnp.floating) and \
                        gd != jax.dtypes.float0:
                    g = g.astype(meta[1])
                out_grads.append(g)

            if capture is not None:
                for i in range(node.num_outputs):
                    key = capture.get((id(node), i))
                    if key is not None:
                        captured[key] = out_grads[i]

            if isinstance(node, GradAccumulationNode):
                if accumulate:
                    in_grads = node.apply([_unwrap(out_grads[0])])
                else:
                    in_grads = []
            elif create_graph and isinstance(node, OpGradNode):
                in_grads = _dispatch_vjp(node, out_grads)
            elif node.wants_tensors:
                in_grads = node.apply([
                    _wrap_grad(g, create_graph) for g in out_grads])
                if not create_graph:
                    in_grads = [_unwrap(g) for g in in_grads]
            else:
                in_grads = node.apply([_unwrap(g) for g in out_grads])
                if create_graph:
                    in_grads = [_wrap_grad(g, False) for g in in_grads]
            if not retain_graph:
                node.release()

        for g, edge in zip(in_grads, node.next_edges):
            if edge is None:
                continue
            tgt = edge.node
            if g is not None:
                slots = pending[tgt]
                slots[edge.slot] = g if edge.slot not in slots \
                    else slots[edge.slot] + g
            # Always decrement: a None gradient still resolves the dependency,
            # otherwise nodes reachable only via non-differentiable paths
            # would stall and leaf grads on other paths would be lost.
            indeg[id(tgt)] -= 1
            if indeg[id(tgt)] == 0:
                ready.append(tgt)

    # Flush any leaf accumulation nodes that became ready only via pending
    # (degenerate graphs where an accumulation node still has in-degree > 0
    # because some producer was unreachable — shouldn't happen, but be safe).
    for node, slots in list(pending.items()):
        if isinstance(node, GradAccumulationNode) and indeg[id(node)] <= 0:
            if capture is not None and (id(node), 0) in capture:
                captured[capture[(id(node), 0)]] = slots.get(0)
            if accumulate:
                node.apply([_unwrap(slots.get(0))])
    return captured if capture is not None else None
