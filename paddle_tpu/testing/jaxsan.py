"""jaxsan: a runtime trace-safety sanitizer (chaos-harness style).

graft-lint's R002/R003 rules catch the *shape* of the two silent-
corruption classes statically; jaxsan turns surviving instances into
immediate loud failures at run time, gated on ``FLAGS_enable_jaxsan``
(default OFF — the disabled paths are a single boolean check, same cost
model as the chaos harness and the metrics gate):

* **In-flight host-buffer checksums** (the PR 3 race class).  A dispatch
  site takes a :func:`token`, routes every host buffer it hands the
  device through :func:`shield` (which checksums it), and calls
  :func:`verify` at its harvest/sync point.  Any in-place mutation of a
  fed buffer between dispatch and harvest raises :class:`JaxsanError`
  naming the site — instead of the program silently reading the mutated
  bytes.  The serving tick loop is wired through this.

* **Donated-leaf poisoning** (the use-after-donate class).  On CPU, jax
  *ignores* donation, so code that reads a donated buffer after the call
  works in every CPU test and corrupts on TPU.  :func:`poison_donated`
  deletes the donated jax buffers the moment the program has returned
  (``Array.delete()`` — any later use raises jax's "deleted" error) and
  garbage-fills donated numpy mirrors, so the latent bug fails loudly in
  CPU CI.  The fused optimizer step is wired through this.

* **Deliberate re-injection** (tests).  :func:`unsafe_alias` makes every
  shielded dispatch skip its private copy — reintroducing the exact
  aliasing race the private copies fix — so a test can prove the
  checksums actually catch the race class (the same arm-then-observe
  discipline as `testing.chaos`).

* **blocksan** (ISSUE 12).  A shadow refcount ledger
  (:class:`BlockLedger`) mirrors every serving-engine
  ``_alloc_block``/``_ref_block``/``_release_block`` call and is
  verified against the engine's OWN data structures at tick boundaries
  (:func:`blocksan_verify`): a double-release raises at the call site,
  a reference the tables/shadow rows/prefix index cannot account for is
  a leak, a structural reference the accounting path never saw is an
  untracked alias, and the free list must be exactly the rc==0 blocks
  with no duplicates.  Prefix-cache-REGISTERED blocks additionally
  carry byte checksums (:func:`blocksan_snapshot`) re-verified every
  boundary, turning the "registered blocks are immutable" contract
  (PR 9/10: CoW, rejected spec drafts) into a runtime invariant instead
  of a test-only parity pin.  All of it rides ``FLAGS_enable_jaxsan``
  (the ledger is created at engine construction; off = one ``is None``
  check per call).
"""

from __future__ import annotations

import hashlib
import threading
from contextlib import contextmanager
from typing import Any, Iterable, List, Optional, Tuple

import numpy as np

__all__ = [
    "JaxsanError", "enabled", "token", "shield", "feed", "verify",
    "poison_donated", "unsafe_alias", "alias_armed",
    "BlockLedger", "block_ledger", "blocksan_snapshot",
    "blocksan_verify",
]


class JaxsanError(RuntimeError):
    """A sanitized invariant was violated (this is the loud failure)."""


# Synced from FLAGS_enable_jaxsan (flags.py installs the hook).
_ENABLED = False
_ALIAS_ARMED = False
_lock = threading.Lock()


def _sync_enabled(value: bool) -> None:
    global _ENABLED
    _ENABLED = bool(value)


def _init_from_flag() -> None:
    try:
        from .. import flags as _flags
        _sync_enabled(_flags.get_flag("enable_jaxsan"))
    except Exception:  # noqa: BLE001 - flag not registered yet
        pass


def enabled() -> bool:
    return _ENABLED


def _counter(name: str, help_: str):
    from ..observability import metrics as _metrics
    return _metrics.counter(name, help_)


def _m_checks():
    return _counter("jaxsan.checks", "host-buffer checksum verifications "
                    "(labels: site)")


def _m_violations():
    return _counter("jaxsan.violations", "sanitizer trips, by kind="
                    "inflight_mutation|use_after_donate (each also "
                    "raised as JaxsanError)")


def _m_poisoned():
    return _counter("jaxsan.poisoned", "donated leaves poisoned after a "
                    "donated program call (labels: site)")


def _digest(arr: np.ndarray) -> bytes:
    return hashlib.sha1(np.ascontiguousarray(arr).tobytes()).digest()


class Token:
    """One dispatch's fed-buffer ledger: (buffer, checksum) pairs."""

    __slots__ = ("site", "entries", "verified")

    def __init__(self, site: str):
        self.site = site
        self.entries: List[Tuple[np.ndarray, bytes]] = []
        self.verified = False

    def feed(self, arr: np.ndarray) -> None:
        self.entries.append((arr, _digest(arr)))


def token(site: str) -> Optional[Token]:
    """Open a ledger for one dispatch; None when the sanitizer is off
    (every other entry point is None-safe, so instrumented sites carry
    zero cost disabled)."""
    return Token(site) if _ENABLED else None


def feed(tok: Optional[Token], arr):
    """Checksum ``arr`` into the ledger (numpy only; passthrough)."""
    if tok is not None and isinstance(arr, np.ndarray):
        tok.feed(arr)
    return arr


def shield(tok: Optional[Token], arr: np.ndarray) -> np.ndarray:
    """The private-copy chokepoint for host buffers handed to an async
    program.  Normal operation returns ``arr.copy()`` (the R002 fix) and
    checksums what the device actually received; under
    :func:`unsafe_alias` the copy is SKIPPED — the original buffer is
    fed and checksummed, so the scheduler's own post-dispatch
    bookkeeping trips :func:`verify` exactly the way the real race
    corrupted real programs."""
    if tok is None:
        return arr.copy()
    buf = arr if _ALIAS_ARMED else arr.copy()
    tok.feed(buf)
    return buf


def verify(tok: Optional[Token]) -> None:
    """The harvest-side check: every fed buffer must still hash to its
    dispatch-time checksum."""
    if tok is None or tok.verified:
        return
    tok.verified = True
    _m_checks().inc(len(tok.entries), site=tok.site)
    for i, (arr, dig) in enumerate(tok.entries):
        if _digest(arr) != dig:
            _m_violations().inc(kind="inflight_mutation")
            raise JaxsanError(
                f"jaxsan [{tok.site}]: host buffer #{i} "
                f"(shape {arr.shape}, {arr.dtype}) was mutated in place "
                "while the dispatched program could still read it — the "
                "device input must be a private copy, or the mutation "
                "must wait for the harvest sync")


@contextmanager
def unsafe_alias():
    """TEST-ONLY: make shielded dispatch sites feed the live buffer
    (no private copy), deliberately reintroducing the aliasing race so
    the checksums can be proven to catch it."""
    global _ALIAS_ARMED
    with _lock:
        prev, _ALIAS_ARMED = _ALIAS_ARMED, True
    try:
        yield
    finally:
        with _lock:
            _ALIAS_ARMED = prev


def alias_armed() -> bool:
    return _ALIAS_ARMED


def poison_donated(leaves: Iterable[Any], site: str = "",
                   keep: Iterable[Any] = ()) -> int:
    """Poison buffers that a just-returned program DONATED (or would
    donate on an accelerator): jax arrays are deleted — any later read
    raises jax's deleted-array error with this call in the stack — and
    numpy mirrors are garbage-filled so stale reads are unmissable.

    ``keep`` guards passthrough aliasing: a leaf that IS one of the
    program's outputs (identity) is never poisoned.  Tracers are skipped
    (under a to_static capture the donation is the captured program's
    business, not this eager call's).  Returns the number of leaves
    poisoned."""
    if not _ENABLED:
        return 0
    import jax
    keep_ids = {id(k) for k in keep}
    seen = set()
    n = 0
    for leaf in leaves:
        if leaf is None or id(leaf) in keep_ids or id(leaf) in seen:
            continue
        seen.add(id(leaf))
        if isinstance(leaf, jax.core.Tracer):
            continue
        if isinstance(leaf, jax.Array):
            try:
                leaf.delete()
                n += 1
            except Exception:  # noqa: BLE001 - already deleted/committed
                pass
        elif isinstance(leaf, np.ndarray) and leaf.flags.writeable:
            if np.issubdtype(leaf.dtype, np.floating):
                leaf.fill(np.nan)
            elif np.issubdtype(leaf.dtype, np.unsignedinteger):
                # .min would be 0 — plausible-looking token/block ids;
                # the poison must be unmissable
                leaf.fill(np.iinfo(leaf.dtype).max)
            elif np.issubdtype(leaf.dtype, np.integer):
                leaf.fill(np.iinfo(leaf.dtype).min)
            elif leaf.dtype == np.bool_:
                leaf.fill(True)
            n += 1
    if n:
        _m_poisoned().inc(n, site=site or "unknown")
    return n


# ===================================================== blocksan (ISSUE 12)

def _violation(kind: str, message: str) -> None:
    _m_violations().inc(kind=kind)
    raise JaxsanError(f"blocksan [{kind}]: {message}")


class BlockLedger:
    """Shadow refcount ledger for one serving engine's physical KV
    blocks.  The engine's accessors report every acquisition/release as
    it happens (``alloc``/``ref``/``release``); the ledger is the
    INDEPENDENT book that :func:`blocksan_verify` reconciles against
    the engine's actual data structures — so a code path that forgets a
    release (or releases twice, or bypasses the accessors) cannot stay
    silent until the pool mysteriously drains in production.

    ``digests`` carries the registered-block byte checksums (block id
    -> sha1 of the block's bytes across every layer's pools, draft
    pools included); a block's digest dies with its last reference —
    a freed-and-reallocated block must never be judged against its
    previous life's bytes."""

    __slots__ = ("rc", "num_blocks", "digests", "verifies")

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        self.rc = np.zeros((num_blocks + 1,), np.int64)
        self.digests: dict = {}
        self.verifies = 0

    def alloc(self, blk: int) -> None:
        if self.rc[blk] != 0:
            _violation(
                "free_list_corrupt",
                f"block {blk} allocated while the ledger still holds "
                f"{int(self.rc[blk])} reference(s) — the free list "
                "handed out a live block")
        self.rc[blk] = 1
        self.digests.pop(blk, None)

    def ref(self, blk: int) -> None:
        if self.rc[blk] <= 0:
            _violation(
                "untracked_reference",
                f"block {blk} re-referenced while the ledger holds no "
                "reference — pinning a block nobody owns aliases the "
                "free pool")
        self.rc[blk] += 1

    def release(self, blk: int) -> None:
        if self.rc[blk] <= 0:
            _violation(
                "double_release",
                f"block {blk} released while the ledger holds no "
                "reference — a double release frees a block some other "
                "holder still reads")
        self.rc[blk] -= 1
        if self.rc[blk] == 0:
            self.digests.pop(blk, None)


def block_ledger(num_blocks: int) -> Optional[BlockLedger]:
    """A ledger when the sanitizer is enabled, else None (every engine
    call site is None-guarded, so the disabled path costs one check)."""
    return BlockLedger(num_blocks) if _ENABLED else None


def _block_digest(engine, blk: int) -> bytes:
    h = hashlib.sha1()
    pool_sets = [engine.pools]
    if getattr(engine, "dpools", None):
        pool_sets.append(engine.dpools)
    for pools in pool_sets:
        for kk, vv in pools:
            h.update(np.asarray(kk[:, blk]).tobytes())
            h.update(np.asarray(vv[:, blk]).tobytes())
    return h.digest()


def blocksan_snapshot(engine) -> None:
    """Checksum every prefix-REGISTERED block not yet in the ledger —
    called right after ``prefix.register``, when the block's bytes are
    ground truth by construction.  Registered blocks are immutable
    (decode always starts in an unregistered column; CoW copies shared
    blocks before writing), so any later digest mismatch is corruption,
    not staleness."""
    led = getattr(engine, "_blocksan", None)
    if led is None or engine.prefix is None:
        return
    for blk in engine.prefix.resident_blocks():
        if blk not in led.digests:
            led.digests[blk] = _block_digest(engine, blk)


def blocksan_verify(engine) -> None:
    """The tick-boundary reconciliation.  Four invariants:

    1. the engine's own ``block_rc`` equals the ledger (no accounting
       path bypassed the accessors);
    2. the free list is exactly the rc==0 blocks, no duplicates;
    3. the ledger equals the STRUCTURAL reference count — table rows +
       chunked-prefill shadow rows + one per prefix-index entry — so a
       held reference nothing points at is a leak, and a structural
       reference the ledger never saw is untracked;
    4. every registered block still hashes to its registration-time
       digest (immutability across decode, rejected spec drafts, CoW).
    """
    led = getattr(engine, "_blocksan", None)
    if led is None:
        return
    led.verifies += 1
    _m_checks().inc(site="serving.blocksan")
    n = engine.num_blocks
    if not np.array_equal(led.rc[1:], engine.block_rc[1:]):
        bad = int(np.nonzero(led.rc[1:] != engine.block_rc[1:])[0][0]) + 1
        _violation(
            "accounting_mismatch",
            f"block {bad}: engine block_rc={int(engine.block_rc[bad])} "
            f"but the ledger saw {int(led.rc[bad])} — some path "
            "mutated refcounts without going through "
            "_alloc/_ref/_release_block")
    free = [int(b) for b in engine.free_blocks]
    if len(free) != len(set(free)):
        dup = sorted(b for b in set(free) if free.count(b) > 1)[0]
        _violation("free_list_corrupt",
                   f"block {dup} appears twice in free_blocks — the "
                   "next two allocations alias one physical block")
    want_free = {b for b in range(1, n + 1) if led.rc[b] == 0}
    if set(free) != want_free:
        ghost = sorted(set(free) ^ want_free)[0]
        _violation(
            "free_list_corrupt",
            f"free_blocks disagrees with the ledger at block {ghost}: "
            f"in free list={ghost in set(free)}, "
            f"ledger rc={int(led.rc[ghost])}")
    expected = np.zeros((n + 1,), np.int64)
    live = engine.tables[engine.tables > 0]
    np.add.at(expected, live.reshape(-1), 1)
    for req in engine.slot_req:
        row = getattr(req, "_chunk_row", None) if req is not None else None
        if row is not None:
            srow = np.asarray(row)
            np.add.at(expected, srow[srow > 0].reshape(-1), 1)
    if engine.prefix is not None:
        for blk in engine.prefix.resident_blocks():
            expected[blk] += 1
    if not np.array_equal(led.rc[1:], expected[1:]):
        idx = np.nonzero(led.rc[1:] != expected[1:])[0] + 1
        leaks = [int(b) for b in idx if led.rc[b] > expected[b]]
        ghosts = [int(b) for b in idx if led.rc[b] < expected[b]]
        if leaks:
            b = leaks[0]
            _violation(
                "block_leak",
                f"block {b} holds {int(led.rc[b])} ledger reference(s) "
                f"but only {int(expected[b])} structural holder(s) "
                "(tables / shadow rows / prefix index) exist — a "
                "release call is missing and the block is pool "
                "capacity lost for the process lifetime")
        b = ghosts[0]
        _violation(
            "untracked_reference",
            f"block {b} is referenced by {int(expected[b])} "
            f"structure(s) but the ledger saw only {int(led.rc[b])} "
            "acquisition(s) — something installed a block id without "
            "going through the accounting path")
    for blk, digest in list(led.digests.items()):
        if _block_digest(engine, blk) != digest:
            _violation(
                "registered_block_mutation",
                f"prefix-registered block {blk} no longer hashes to "
                "its registration-time bytes — a decode/spec-draft/CoW "
                "write landed in an immutable shared block; every "
                "request sharing this prefix now reads corrupt KV")


_init_from_flag()
