"""Observability subsystem: metrics registry semantics, instrumentation
hooks in the hot layers, the span API, and the perf-evidence harness's
degradation guarantees (ISSUE 1)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability import harness, metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_metrics():
    metrics.reset()
    yield
    paddle.set_flags({"enable_metrics": True})
    metrics.reset()


# ------------------------------------------------------------------- core

def test_counter_semantics():
    c = metrics.counter("t.counter", "help text")
    c.inc()
    c.inc(2)
    c.inc(op="add")
    c.inc(3, op="add")
    c.inc(op="mul")
    assert c.value() == 3
    assert c.value(op="add") == 4
    assert c.value(op="mul") == 1
    assert c.total() == 8
    snap = metrics.snapshot()["t.counter"]
    assert snap["type"] == "counter" and snap["help"] == "help text"
    assert {"labels": {"op": "mul"}, "value": 1} in snap["series"]


def test_counter_get_or_create_idempotent():
    a = metrics.counter("t.same")
    b = metrics.counter("t.same")
    assert a is b
    with pytest.raises(ValueError):
        metrics.gauge("t.same")


def test_gauge_semantics():
    g = metrics.gauge("t.gauge")
    assert g.value() is None
    g.set(0.5)
    g.set(0.75)
    assert g.value() == 0.75
    g.inc(0.25)
    g.dec(0.5)
    assert abs(g.value() - 0.5) < 1e-9
    g.set(3, slot="a")
    assert g.value(slot="a") == 3


def test_histogram_semantics():
    h = metrics.histogram("t.hist", buckets=[0.1, 1.0, 10.0])
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count() == 4
    assert abs(h.sum() - 55.55) < 1e-9
    val = metrics.snapshot()["t.hist"]["series"][0]["value"]
    assert val["count"] == 4
    assert val["min"] == 0.05 and val["max"] == 50.0
    assert val["buckets"] == {"0.1": 1, "1.0": 1, "10.0": 1, "+inf": 1}
    assert abs(val["mean"] - 55.55 / 4) < 1e-9


def test_label_cardinality_overflow():
    c = metrics.counter("t.cardinality")
    limit = type(c).MAX_SERIES
    for i in range(limit + 10):
        c.inc(rid=i)
    snap = metrics.snapshot()["t.cardinality"]["series"]
    assert len(snap) == limit + 1          # capped + one overflow series
    overflow = [s for s in snap if s["labels"] == {"__overflow__": "true"}]
    assert overflow and overflow[0]["value"] == 10


def test_disabled_mode_is_noop():
    c = metrics.counter("t.disabled")
    h = metrics.histogram("t.disabled_h")
    paddle.set_flags({"enable_metrics": False})
    assert not metrics.enabled()
    c.inc()
    c.inc_key((("op", "x"),))
    h.observe(1.0)
    metrics.gauge("t.disabled_g").set(5)
    assert metrics.snapshot() == {}
    paddle.set_flags({"enable_metrics": True})
    assert metrics.enabled()
    c.inc()
    assert c.total() == 1


def test_reset_keeps_definitions():
    c = metrics.counter("t.reset")
    c.inc(5)
    metrics.reset()
    assert metrics.counter("t.reset") is c
    assert c.total() == 0
    assert "t.reset" not in metrics.snapshot()  # no data -> omitted


def test_export_json(tmp_path):
    metrics.counter("t.export").inc(7, kind="x")
    path = tmp_path / "metrics.json"
    text = metrics.export_json(str(path))
    doc = json.loads(path.read_text())
    assert json.loads(text) == doc
    assert doc["schema"] == "paddle_tpu.metrics/v1"
    assert doc["metrics"]["t.export"]["series"][0]["value"] == 7


def test_span_histogram_and_chrome_trace(tmp_path):
    from paddle_tpu.profiler import Profiler
    with obs.span("outside_profiler"):
        pass
    h = metrics.get("spans.seconds")
    assert h.count(name="outside_profiler") == 1
    # inside a recording profiler the span lands on the host timeline
    with Profiler() as p:
        with obs.span("inside_profiler"):
            sum(range(100))
        path = p.export(str(tmp_path / "trace.json"))
    events = json.load(open(path))["traceEvents"]
    assert any(e["name"] == "inside_profiler" and e["cat"] == "span"
               for e in events)


# -------------------------------------------------------- instrumentation

def test_dispatch_instrumentation():
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    y = paddle.add(paddle.multiply(x, x), x)
    del y
    ops = metrics.get("dispatch.ops")
    assert ops.value(op="add") >= 1
    assert ops.value(op="multiply") >= 1
    fp = metrics.get("dispatch.fastpath")
    assert fp.total() >= 1  # hits and/or misses were recorded


def test_jit_compile_metrics():
    from paddle_tpu.jit import to_static

    @to_static
    def f(a):
        return a * 2 + 1

    x = paddle.to_tensor(np.ones((3,), np.float32))
    f(x)
    f(x)  # cache hit: no new trace
    traces = metrics.get("jit.traces")
    assert traces.value(fn="f") == 1
    comp = metrics.get("jit.compile_seconds")
    assert comp.count(fn="f", stage="trace") == 1
    assert comp.count(fn="f", stage="compile") == 1


def test_collective_instrumentation():
    from paddle_tpu import distributed as dist
    x = paddle.to_tensor(np.ones((8, 4), np.float32))
    dist.all_reduce(x)          # single-rank no-op, still counted
    dist.broadcast(x, src=0)
    calls = metrics.get("collective.calls")
    assert calls.value(op="all_reduce") == 1
    assert calls.value(op="broadcast") == 1
    nbytes = metrics.get("collective.bytes")
    assert nbytes.value(op="all_reduce") == 8 * 4 * 4


def test_serving_instrumentation_and_export(tmp_path):
    from paddle_tpu.inference.serving import Request, ServingEngine
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
    paddle.seed(0)
    model = GPTForCausalLM(gpt3_tiny())
    model.eval()
    eng = ServingEngine(model, max_batch=2, max_context=64, block_size=16)
    rng = np.random.RandomState(0)
    eng.add_request(Request(rng.randint(1, 100, (8,)), max_new_tokens=4))
    eng.run()
    snap = metrics.snapshot()
    assert snap["serving.admissions"]["series"][0]["value"] == 1
    assert snap["serving.tokens_out"]["series"][0]["value"] >= 4
    assert snap["serving.ticks"]["series"][0]["value"] >= 1
    assert "serving.pool_occupancy" in snap
    assert "serving.tokens_per_sec" in snap
    # exportable as JSON (acceptance: non-empty snapshot -> artifact)
    doc = json.loads(metrics.export_json(str(tmp_path / "m.json")))
    assert doc["metrics"]["serving.tokens_out"]["series"][0]["value"] >= 4


def test_serving_rejection_metrics():
    from paddle_tpu.inference.serving import Request, ServingEngine
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
    paddle.seed(0)
    model = GPTForCausalLM(gpt3_tiny())
    model.eval()
    eng = ServingEngine(model, max_batch=1, max_context=32, block_size=16)
    with pytest.raises(ValueError):
        eng.add_request(Request(np.arange(1, 30), max_new_tokens=16))
    rej = metrics.get("serving.rejections")
    assert rej.value(reason="over_context") == 1
    # worst-case block need beyond the WHOLE pool: a capacity rejection
    eng2 = ServingEngine(model, max_batch=1, max_context=64, block_size=16,
                         num_blocks=2)
    with pytest.raises(ValueError):
        eng2.add_request(Request(np.arange(1, 17), max_new_tokens=40))
    assert rej.value(reason="capacity") == 1


def test_train_step_latency_histogram():
    from paddle_tpu import nn, optimizer
    from paddle_tpu.hapi import Model

    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m = Model(net)
    m.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                      parameters=net.parameters()),
              loss=nn.CrossEntropyLoss())
    x = np.random.RandomState(0).rand(4, 4).astype(np.float32)
    y = np.array([0, 1, 0, 1], np.int64)
    m.train_batch([x], [y])
    m.train_batch([x], [y])
    h = metrics.get("train.step_seconds")
    assert h.count(mode="train") == 2


# ----------------------------------------------------------------- harness

def _fail_devices(monkeypatch):
    import jax

    def boom():
        raise RuntimeError("no backend: simulated tunnel outage")
    monkeypatch.setattr(jax, "devices", boom)


def test_probe_backend_survives_raising_devices(monkeypatch):
    _fail_devices(monkeypatch)
    probe = harness.probe_backend()
    assert probe["ok"] is False
    assert "simulated tunnel outage" in probe["error"]


def test_harness_degradation(monkeypatch):
    """Backend gone: TPU rungs degrade to backend_unavailable, CPU rungs
    still run and emit real measurements, a raising rung emits an error
    record — every record schema-valid, nothing raises."""
    _fail_devices(monkeypatch)

    @harness.register_rung("_t_tpu_only", requires="tpu")
    def tpu_rung(ctx):
        raise AssertionError("must not run")

    @harness.register_rung("_t_cpu_ok")
    def cpu_rung(ctx):
        assert ctx.on_tpu is False
        return {"answer": 42}

    @harness.register_rung("_t_cpu_boom")
    def cpu_boom(ctx):
        raise ValueError("inner rung failure")

    try:
        recs = harness.run(["_t_tpu_only", "_t_cpu_ok", "_t_cpu_boom"])
    finally:
        for n in ("_t_tpu_only", "_t_cpu_ok", "_t_cpu_boom"):
            harness._REGISTRY.pop(n, None)
    by = {r["rung"]: r for r in recs}
    assert by["_t_tpu_only"]["ok"] is False
    assert by["_t_tpu_only"]["reason"] == "backend_unavailable"
    assert by["_t_cpu_ok"]["ok"] is True
    assert by["_t_cpu_ok"]["value"] == {"answer": 42}
    assert by["_t_cpu_boom"]["ok"] is False
    assert "inner rung failure" in by["_t_cpu_boom"]["error"]
    for r in recs:
        assert harness.validate_record(r) is None, harness.validate_record(r)


def test_harness_budget_and_smoke_gates():
    @harness.register_rung("_t_costly", est_cold_s=1000)
    def costly(ctx):
        return {}

    @harness.register_rung("_t_smokeless")
    def smokeless(ctx):
        return {}

    try:
        rec = harness.run_rung(harness.get_rung("_t_costly"),
                               budget_left=lambda: 5.0)
        assert rec["ok"] is False and rec["reason"] == "budget"
        rec = harness.run_rung(harness.get_rung("_t_smokeless"), smoke=True)
        assert rec["ok"] is False and rec["reason"] == "skipped_smoke"
    finally:
        harness._REGISTRY.pop("_t_costly", None)
        harness._REGISTRY.pop("_t_smokeless", None)


def test_validate_record_rejects_malformed():
    assert harness.validate_record("nope") is not None
    assert harness.validate_record({}) is not None
    assert harness.validate_record(
        {"rung": "x", "ok": True, "device": "cpu",
         "elapsed_s": 0.1}) is not None      # ok without value
    assert harness.validate_record(
        {"rung": "x", "ok": False, "device": "cpu",
         "elapsed_s": 0.1}) is not None      # degraded without reason
    assert harness.validate_record(
        {"rung": "x", "ok": True, "device": "cpu", "elapsed_s": 0.1,
         "value": {"a": 1}}) is None


def test_regression_check_reads_both_artifact_generations(tmp_path):
    prev = tmp_path / "BENCH_r99.json"
    prev.write_text(json.dumps({
        "tail": "\n".join([
            json.dumps({"bench": "gpt124m_train", "tokens_per_sec": 100.0}),
            json.dumps({"rung": "lenet_train", "ok": True, "device": "x",
                        "elapsed_s": 1.0,
                        "value": {"jit_imgs_per_sec": 200.0}}),
        ])}))
    current = [
        {"rung": "gpt124m_train", "ok": True, "device": "x",
         "elapsed_s": 1.0, "value": {"tokens_per_sec": 50.0}},
        {"rung": "lenet_train", "ok": True, "device": "x",
         "elapsed_s": 1.0, "value": {"jit_imgs_per_sec": 220.0}},
    ]
    out = harness.regression_check(
        current, previous=str(prev),
        keys={"gpt124m_train": "tokens_per_sec",
              "lenet_train": "jit_imgs_per_sec"})
    assert out["rel_delta"]["gpt124m_train"] == -0.5
    assert out["rel_delta"]["lenet_train"] == 0.1
    assert out["regressed"] == ["gpt124m_train"]


# ------------------------------------------------------------ bench driver

def _import_bench():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow   # tier-1 budget (ISSUE 9): heavy, not on the serving/training core path
def test_bench_backend_unavailable_exits_zero(monkeypatch, tmp_path,
                                              capsys):
    """Acceptance: with `jax.devices` raising, bench.py exits 0 and the
    artifact holds ok:false backend_unavailable records for TPU rungs and
    real measurements for the CPU rungs."""
    bench = _import_bench()
    _fail_devices(monkeypatch)
    art = tmp_path / "artifact.json"
    rc = bench.main(["--rungs", "all", "--smoke", "--out", str(art)])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    headline = json.loads(out[-1])
    assert headline["metric"] == "gpt124m_train_tokens_per_sec"
    doc = json.loads(art.read_text())
    assert doc["backend"]["ok"] is False
    recs = {r["rung"]: r for r in doc["records"]}
    for r in doc["records"]:
        assert harness.validate_record(r) is None, harness.validate_record(r)
    # every TPU-only rung degraded, none crashed the run
    for name in ("tuner_memory_validation", "gpt124m_decode_32k_config",
                 "gpt350m_train"):
        assert recs[name]["ok"] is False
        assert recs[name]["reason"] == "backend_unavailable"
    # the CPU-salvageable smoke rungs produced real measurements
    for name in ("dispatch_overhead", "serving_continuous_batching",
                 "ring_attention_8k", "metrics_overhead",
                 "telemetry_train"):
        assert recs[name]["ok"] is True, recs[name]
        assert recs[name]["value"], name
        # ISSUE 2: every bench rung record self-evidences with its own
        # metrics delta
        assert isinstance(recs[name].get("metrics"), dict), name
    # the telemetry rung embeds a StepTimeline summary with fractions +
    # MFU from the shared FLOPs helper
    summ = recs["telemetry_train"]["value"]["timeline"]
    assert set(summ["fractions"]) == {"compute", "comm", "host"}
    assert "mfu" in summ and summ["steps"] >= 1


@pytest.mark.slow   # tier-1 budget (R010): 30-100s bench child, env-flaky
def test_bench_cpu_smoke_subprocess(tmp_path):
    """CI/tooling satellite: `python bench.py --rungs cpu --smoke` runs in
    seconds on CPU, exits 0, and every rung emits schema-valid JSON."""
    art = tmp_path / "smoke.json"
    # budget/timeout sized for the grown smoke ladder (cold_start spawns
    # two nested interpreters) on a co-tenant-loaded box; the bench's
    # own budget gate degrades tail rungs to reason:"budget" before the
    # hard timeout can fire
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_BUDGET_S="450")
    env.pop("XLA_FLAGS", None)
    # one bounded retry on ABNORMAL-SIGNAL exits only: this container's
    # XLA CPU runtime segfaults/aborts the child ~50% of runs (rc -6/-11
    # or the 128+signal shell form; verified environmental on pristine
    # HEAD) and a rerun passes.  A real harness failure exits rc=1 and
    # must stay loud on the first attempt.
    for attempt in (0, 1):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--rungs", "cpu", "--smoke", "--out", str(art)],
            capture_output=True, text=True, timeout=560, cwd=REPO,
            env=env)
        if proc.returncode == 0 or attempt == 1 \
                or not (proc.returncode < 0 or proc.returncode > 128):
            break
    assert proc.returncode == 0, proc.stderr[-2000:]
    headline = json.loads(proc.stdout.strip().splitlines()[-1])
    assert headline["metric"] == "gpt124m_train_tokens_per_sec"
    doc = json.loads(art.read_text())
    assert doc["schema"] == harness.SCHEMA
    names = set()
    ok_names = set()
    for rec in doc["records"]:
        assert harness.validate_record(rec) is None, \
            (rec, harness.validate_record(rec))
        names.add(rec["rung"])
        if rec["ok"]:
            ok_names.add(rec["rung"])
    # the named CPU rungs really measured (ISSUE acceptance)
    assert {"dispatch_overhead", "serving_continuous_batching",
            "ring_attention_8k", "telemetry_train"} <= ok_names
    # ISSUE 2 acceptance: per-rung records carry a metrics snapshot and
    # the telemetry rung a StepTimeline summary (fractions + MFU)
    recs = {r["rung"]: r for r in doc["records"]}
    for name in ok_names:
        assert isinstance(recs[name].get("metrics"), dict), name
    summ = recs["telemetry_train"]["value"]["timeline"]
    assert set(summ["fractions"]) == {"compute", "comm", "host"}
    assert abs(sum(summ["fractions"].values()) - 1.0) < 0.02
    assert isinstance(summ.get("mfu"), float)
    assert summ["flops_per_token"] > 0 and summ["peak_flops"] > 0
    # stderr carried one JSON line per rung
    stderr_rungs = {json.loads(line)["rung"]
                    for line in proc.stderr.splitlines()
                    if line.startswith("{")}
    assert names <= stderr_rungs
