"""paddle.distributed.sharding namespace (group_sharded_parallel entry).
Parity: `python/paddle/distributed/sharding/group_sharded.py`."""

from ..fleet.sharding import group_sharded_parallel  # noqa: F401


def save_group_sharded_model(model, output, optimizer=None):
    from ...framework.io import save
    import os
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
