"""flight — the serving fleet operator CLI.

Subcommands:

``route``
    Start a :class:`~paddle_tpu.inference.fleet.FleetRouter` in front
    of running engine replicas and serve until SIGINT::

        python -m paddle_tpu.flight route \\
            --replica r0=127.0.0.1:8101 --replica r1=127.0.0.1:8102 \\
            --port 8100 --ttft-budget-ms 500

    ``--demo N`` instead spins up N in-process tiny-model replicas
    (CPU, loopback) behind the router — the simulated fleet the tests
    and the ``fleet`` bench rung use, handy for poking the HTTP surface
    without real deployments.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict


def _parse_replicas(vals) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for i, v in enumerate(vals or ()):
        name, eq, addr = v.partition("=")
        if not eq:
            name, addr = f"r{i}", v
        if ":" not in addr:
            raise SystemExit(f"--replica wants [name=]host:port, got {v!r}")
        out[name] = addr
    return out


def _demo_fleet(n: int, tmp_root: str, **router_kw):
    from .framework import random as _random
    from .inference.fleet import Fleet
    from .inference.serving import ServingEngine
    from .models.gpt import GPTForCausalLM, gpt3_tiny

    def factory(export_dir: str) -> ServingEngine:
        # one model instance PER replica (same seed, identical
        # weights): concurrent engines must not share a model object —
        # see inference/fleet/replica.py
        _random.seed(0)
        model = GPTForCausalLM(gpt3_tiny())
        model.eval()
        return ServingEngine(model, max_batch=2, max_context=64,
                             block_size=16,
                             prefix_export_dir=export_dir)

    return Fleet.build(factory, n, tmp_root, **router_kw)


def cmd_route(args: argparse.Namespace) -> int:
    from .inference.fleet import FleetRouter

    fleet = None
    if args.demo:
        import tempfile
        root = tempfile.mkdtemp(prefix="flight-demo-")
        print(f"starting {args.demo} in-process demo replicas "
              f"(export root {root}) ...", flush=True)
        fleet = _demo_fleet(args.demo, root, port=args.port,
                            affinity_tokens=args.affinity_tokens,
                            ttft_budget_ms=args.ttft_budget_ms,
                            poll_interval_s=args.poll_interval_s)
        router = fleet.router
    else:
        replicas = _parse_replicas(args.replica)
        if not replicas:
            raise SystemExit("route needs --replica [name=]host:port "
                             "(repeatable) or --demo N")
        router = FleetRouter(
            replicas, port=args.port,
            affinity_tokens=args.affinity_tokens,
            ttft_budget_ms=args.ttft_budget_ms,
            poll_interval_s=args.poll_interval_s)
    print(f"fleet router listening on {router.url}", flush=True)
    print(json.dumps(router.describe(), indent=2), flush=True)
    try:
        while True:
            time.sleep(10.0)
            stats = router.stats()
            if stats["routed"]:
                print(json.dumps(stats), flush=True)
    except KeyboardInterrupt:
        print("\nshutting down ...", flush=True)
    finally:
        if fleet is not None:
            fleet.close()
        else:
            router.close()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.flight",
        description="serving fleet operator CLI")
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("route", help="run a prefix-affinity fleet router")
    r.add_argument("--replica", action="append", metavar="[NAME=]HOST:PORT",
                   help="engine replica frontend (repeatable)")
    r.add_argument("--port", type=int, default=None,
                   help="router port (default FLAGS_fleet_router_port; "
                        "0 = ephemeral)")
    r.add_argument("--affinity-tokens", type=int, default=None,
                   help="prompt tokens hashed into the affinity key "
                        "(default FLAGS_fleet_affinity_tokens)")
    r.add_argument("--ttft-budget-ms", type=float, default=None,
                   help="shed 429 when every replica predicts TTFT over "
                        "this budget (0 disables; default "
                        "FLAGS_fleet_ttft_budget_ms)")
    r.add_argument("--poll-interval-s", type=float, default=None,
                   help="healthz poll cadence (default "
                        "FLAGS_fleet_poll_interval_s)")
    r.add_argument("--demo", type=int, default=0, metavar="N",
                   help="spin up N in-process tiny-model replicas instead "
                        "of external --replica targets")
    r.set_defaults(fn=cmd_route)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
