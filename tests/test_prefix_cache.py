"""Prefix/KV-cache reuse over the serving block table (ISSUE 9
tentpole part b: `inference/prefix_cache.py` + ServingEngine admission).

The contract: an admission whose prompt prefix is resident skips
prefill for the shared FULL blocks (a block-table pointer copy + a
suffix-only prefill program), sharing is refcounted (eviction frees
only orphaned blocks), a shared block that must be written is
copy-on-written first, and the hit path is observable — counters, a
`prefix_cache` stats section, and visibly smaller prefill/TTFT in the
request traces.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.flags import flag_guard
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt3_tiny())
    m.eval()
    return m


def _sys_prompt(n=32, seed=3):
    return list(np.random.RandomState(seed).randint(1, 1000, (n,)))


def test_hit_reuses_blocks_and_matches_miss_stream(model):
    """Shared-system-prompt traffic: the first request misses and
    registers its full prompt blocks; followers hit, reuse them, and
    decode the SAME tokens a prefill-per-request engine produces."""
    sysp = _sys_prompt()
    eng = ServingEngine(model, max_batch=2, max_context=128,
                        block_size=16, prefix_cache=True)
    a = eng.add_request(Request(sysp + [7, 8, 9], max_new_tokens=5))
    eng.run()
    b = eng.add_request(Request(sysp + [11, 12], max_new_tokens=5))
    eng.run()
    c = eng.add_request(Request(sysp + [7, 8, 9], max_new_tokens=5))
    eng.run()
    st = eng.stats()["prefix_cache"]
    assert st["misses"] == 1 and st["hits"] == 2
    assert st["blocks_shared"] == 4          # 2 followers x 2 blocks
    assert st["entries"] >= 2
    assert a.output_ids == c.output_ids      # same prompt, same stream
    assert b._prefix_blocks == 2 and a._prefix_blocks == 0

    off = ServingEngine(model, max_batch=2, max_context=128,
                        block_size=16, prefix_cache=False)
    b2 = off.add_request(Request(sysp + [11, 12], max_new_tokens=5))
    off.run()
    assert b.output_ids == b2.output_ids
    assert "prefix_cache" not in off.stats()
    # nothing leaked either way: index-held blocks are reclaimable-free
    assert eng.stats()["free_blocks"] == eng.num_blocks
    assert eng.stats()["reserved"] == 0


@pytest.mark.slow  # 8s measured (PR 18 re-budget): third engine-run of the file; the hit/miss stream pin + eviction accounting keep fast coverage
def test_fully_cached_prompt_takes_copy_on_write(model):
    """A follower whose ENTIRE prompt is resident still recomputes the
    last token (its logits are the first output) — into a
    copy-on-written private block, never the shared one."""
    sysp = _sys_prompt(n=32, seed=4)
    eng = ServingEngine(model, max_batch=2, max_context=64,
                        block_size=16, prefix_cache=True)
    r1 = eng.add_request(Request(sysp, max_new_tokens=6))
    eng.run()
    shared_block = int(eng.stats()["prefix_cache"]["entries"]) and \
        eng.prefix.resident_blocks()[-1]
    r2 = eng.add_request(Request(sysp, max_new_tokens=6))
    eng.run()
    st = eng.stats()["prefix_cache"]
    assert st["hits"] == 1
    # 1 fully shared block + the CoW source of the partially reused one
    assert st["blocks_shared"] == 2
    assert r2.output_ids == r1.output_ids
    # the shared block is still indexed (the CoW copy was private)
    assert shared_block in eng.prefix.resident_blocks()
    assert eng.stats()["free_blocks"] == eng.num_blocks


def test_refcounts_survive_concurrent_sharing_and_eviction(model):
    """Two running requests share prefix blocks; evicting one leaves the
    blocks alive for the other and for the index — freed only when the
    last reference drops."""
    sysp = _sys_prompt(n=32, seed=5)
    eng = ServingEngine(model, max_batch=2, max_context=128,
                        block_size=16, prefix_cache=True)
    r1 = eng.add_request(Request(sysp + [5], max_new_tokens=12))
    eng.step()                               # r1 admitted + decoding
    r2 = eng.add_request(Request(sysp + [6], max_new_tokens=2))
    eng.run()                                # r2 joins, hits, finishes
    assert r1.done and r2.done
    assert eng.stats()["prefix_cache"]["hits"] == 1
    # all table references dropped; the 2 shared blocks live on in the
    # index with refcount exactly 1 each
    resident = eng.prefix.resident_blocks()
    assert len(resident) == 2
    assert all(int(eng.block_rc[b]) == 1 for b in resident)
    assert eng.stats()["free_blocks"] == eng.num_blocks


def test_index_eviction_frees_only_orphaned_blocks(model):
    """Pool pressure evicts LRU leaf entries; the admission then fits.
    Blocks still referenced by a running table must survive."""
    sysp = _sys_prompt(n=32, seed=6)
    # pool of exactly 6 blocks: one 32-token prompt + budget fills most
    eng = ServingEngine(model, max_batch=2, max_context=96,
                        block_size=16, num_blocks=6, prefix_cache=True)
    r1 = eng.add_request(Request(sysp, max_new_tokens=4))
    eng.run()
    assert len(eng.prefix.resident_blocks()) == 2
    # a fat unrelated request needs the whole pool -> index must yield
    fat = list(np.random.RandomState(7).randint(1, 1000, (64,)))
    r2 = eng.add_request(Request(fat, max_new_tokens=16))
    eng.run()
    assert r2.done and len(r2.output_ids) == 16
    assert eng.stats()["prefix_cache"]["evictions"] >= 1
    assert eng.stats()["free_blocks"] == eng.num_blocks


def test_eviction_skips_entries_shared_with_running_requests(model):
    """Pool-pressure eviction must not destroy index entries whose
    blocks are still table-referenced: freeing them gains no capacity
    (the block survives its index reference), it would only cold-start
    a hot prefix."""
    from paddle_tpu.inference.prefix_cache import PrefixCache
    pc = PrefixCache(block_size=2)
    rc = {10: 2, 11: 1}      # block 10 shared with a running table
    pc.register([1, 2, 3, 4], [10, 11], lambda b: None)
    freed = pc.evict(5, deref=lambda b: rc[b] == 1,
                     freeable=lambda b: rc[b] == 1)
    # only the orphaned leaf (block 11) went; the shared root survived
    assert freed == 1
    assert pc.resident_blocks() == [10]
    assert pc.evictions == 1


@pytest.mark.slow  # 7s measured: wall-clock speedup assertion needs a quiet box; block-reuse accounting keeps the fast hit pin
def test_hit_prefill_visibly_faster_in_request_traces(model):
    """ISSUE 9 acceptance: TTFT for hit-requests measurably below
    miss-requests, read from the PR 6 lifecycle traces.  Programs are
    warmed by a throwaway miss+hit pair first so the comparison is
    allocation+compute, not compilation."""
    from paddle_tpu.observability import metrics as obs_metrics
    sysp = _sys_prompt(n=48, seed=8)
    with flag_guard(enable_metrics=True):
        obs_metrics.reset()
        eng = ServingEngine(model, max_batch=2, max_context=128,
                            block_size=16, prefix_cache=True)
        w1 = eng.add_request(Request(sysp + [1, 2], max_new_tokens=2))
        eng.run()                            # compiles full prefill
        w2 = eng.add_request(Request(sysp + [3], max_new_tokens=2))
        eng.run()                            # compiles suffix prefill
        assert w1.done and w2.done
        miss_eng = ServingEngine(model, max_batch=2, max_context=128,
                                 block_size=16, prefix_cache=False)
        m1 = miss_eng.add_request(Request(sysp + [9, 1], max_new_tokens=2))
        miss_eng.run()                       # warm its prefill too
        misses, hits = [], []
        for i in range(4):
            m = miss_eng.add_request(
                Request(sysp + [20 + i], max_new_tokens=2))
            miss_eng.run()
            misses.append(m.trace["prefill_s"])
            h = eng.add_request(Request(sysp + [40 + i], max_new_tokens=2))
            eng.run()
            hits.append(h.trace["prefill_s"])
            assert h._prefix_blocks == 3     # 48-token shared prefix
    hit_med, miss_med = np.median(hits), np.median(misses)
    assert hit_med < miss_med, (hits, misses)


def test_chunk_view_attention_matches_from_scratch_oracle():
    """PagedChunkView unit contract: writing a sequence in two chunks
    (prefix then suffix at an offset) yields the same attention output
    for the suffix queries as a dense causal pass over the whole
    sequence would."""
    import jax.numpy as jnp
    from paddle_tpu.models.kv_cache import PagedChunkView, _dense_causal
    rng = np.random.RandomState(0)
    nh, hd, bs, nb = 2, 8, 4, 4
    L1, L2 = 4, 5                       # prefix fills 1 block, suffix spans
    L = L1 + L2
    q = rng.randn(1, L, nh, hd).astype(np.float32)
    k = rng.randn(1, L, nh, hd).astype(np.float32)
    v = rng.randn(1, L, nh, hd).astype(np.float32)
    pools = (jnp.zeros((nh, nb + 1, bs, hd), jnp.float32),
             jnp.zeros((nh, nb + 1, bs, hd), jnp.float32))
    tables = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    view = PagedChunkView.from_parts(pools[0], pools[1], tables,
                                     jnp.zeros((1,), jnp.int32), bs)
    view, _ = view.update_and_attend(jnp.asarray(q[:, :L1]),
                                     jnp.asarray(k[:, :L1]),
                                     jnp.asarray(v[:, :L1]))
    view2 = PagedChunkView.from_parts(view.k, view.v, tables,
                                      jnp.full((1,), L1, jnp.int32), bs)
    _, out = view2.update_and_attend(jnp.asarray(q[:, L1:]),
                                     jnp.asarray(k[:, L1:]),
                                     jnp.asarray(v[:, L1:]))
    want = _dense_causal(jnp.asarray(q), jnp.asarray(k),
                         jnp.asarray(v))[:, L1:]
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_chunk_view_gqa_head_repeat_matches_dense_oracle():
    """ISSUE 12 satellite: the PR 11 GQA path — `PagedChunkView` hands
    over UN-repeated kv heads (kv_heads < query heads) and the view
    repeats them to the pool's per-query-head layout.  Until now this
    rode only through Llama composition tests; pin it directly against
    the dense oracle (repeat kv, causal attention at the offset)."""
    import jax.numpy as jnp
    from paddle_tpu.models.kv_cache import PagedChunkView, _dense_causal
    rng = np.random.RandomState(1)
    nh, kvh, hd, bs, nb = 4, 2, 8, 4, 4     # 2 query heads per kv head
    L1, L2 = 4, 5
    L = L1 + L2
    q = rng.randn(1, L, nh, hd).astype(np.float32)
    k = rng.randn(1, L, kvh, hd).astype(np.float32)
    v = rng.randn(1, L, kvh, hd).astype(np.float32)
    pools = (jnp.zeros((nh, nb + 1, bs, hd), jnp.float32),
             jnp.zeros((nh, nb + 1, bs, hd), jnp.float32))
    tables = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    view = PagedChunkView.from_parts(pools[0], pools[1], tables,
                                     jnp.zeros((1,), jnp.int32), bs)
    view, _ = view.update_and_attend(jnp.asarray(q[:, :L1]),
                                     jnp.asarray(k[:, :L1]),
                                     jnp.asarray(v[:, :L1]))
    view2 = PagedChunkView.from_parts(view.k, view.v, tables,
                                      jnp.full((1,), L1, jnp.int32), bs)
    _, out = view2.update_and_attend(jnp.asarray(q[:, L1:]),
                                     jnp.asarray(k[:, L1:]),
                                     jnp.asarray(v[:, L1:]))
    rep = nh // kvh
    k_rep = np.repeat(k, rep, axis=2)
    v_rep = np.repeat(v, rep, axis=2)
    want = _dense_causal(jnp.asarray(q), jnp.asarray(k_rep),
                         jnp.asarray(v_rep))[:, L1:]
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # the kv-head count must divide the query heads — anything else is
    # a loud error, not a silent wrong repeat
    bad = PagedChunkView.from_parts(pools[0], pools[1], tables,
                                    jnp.zeros((1,), jnp.int32), bs)
    with np.testing.assert_raises(ValueError):
        bad.update_and_attend(jnp.asarray(q[:, :L1]),
                              jnp.asarray(k[:, :L1, :1][:, :, [0, 0, 0]]),
                              jnp.asarray(v[:, :L1, :1][:, :, [0, 0, 0]]))


def test_prefix_counters_on_metrics_and_prometheus(model):
    """Satellite: serving.prefix_* counters feed the registry snapshot
    and the /metrics exposition, gated on FLAGS_enable_metrics."""
    from paddle_tpu.observability import export as obs_export
    from paddle_tpu.observability import metrics as obs_metrics
    sysp = _sys_prompt(n=32, seed=11)
    with flag_guard(enable_metrics=True):
        obs_metrics.reset()
        eng = ServingEngine(model, max_batch=2, max_context=64,
                            block_size=16, prefix_cache=True)
        eng.add_request(Request(sysp + [1], max_new_tokens=2))
        eng.run()
        eng.add_request(Request(sysp + [2], max_new_tokens=2))
        eng.run()
        snap = obs_metrics.snapshot()
        assert snap["serving.prefix_hits"]["series"][0]["value"] == 1
        assert snap["serving.prefix_misses"]["series"][0]["value"] == 1
        assert snap["serving.prefix_blocks_shared"]["series"][0]["value"] \
            == 2
        text = obs_export.render_prometheus()
        assert "serving_prefix_hits 1" in text
        assert "serving_prefix_misses 1" in text
        assert "serving_prefix_blocks_shared 2" in text
