"""Refcounted prompt-prefix index over the serving block table.

Seat of the reference serving stack's shared-prompt optimization (the
"system prompt" cache every production deployment of
`analysis_predictor.h`-style engines grows): at "millions of users"
scale most traffic shares a long system prompt, and the KV values of a
prompt PREFIX are a pure function of the prefix tokens (causal
attention — position i's K/V never sees position j > i).  So prefill
for a resident prefix is a block-table pointer copy, not a forward
pass.

Design (host-side, like all serving scheduler state):

* The unit of sharing is one FULL physical block (``block_size``
  tokens).  Each index entry maps a hash CHAIN over the prompt's block
  contents — ``h_i = blake2b(h_{i-1} || tokens of block i)`` — to the
  physical block holding those tokens' KV.  Chaining makes an entry
  mean "this exact prefix", not "this 16-gram anywhere".
* Blocks are REFCOUNTED by the engine (table references + one
  reference per index entry).  The index never frees anything itself:
  eviction releases the entry's reference and the engine frees the
  block only when orphaned (refcount 0) — a block still referenced by
  a running request's table survives its index entry.
* Entries are evicted leaf-first in LRU order (an interior entry's
  chain hash is unreachable once its parent is gone, so parents hold a
  child count and only childless entries are evictable).
* Registered blocks are IMMUTABLE by construction: the engine only
  registers blocks every position of which is a prompt token strictly
  before the first decode write, and admission copy-on-writes any
  shared block it must write into.  Nothing here needs device sync.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = ["PrefixCache", "Match"]


class _Entry:
    __slots__ = ("block", "parent", "children")

    def __init__(self, block: int, parent: Optional[bytes]):
        self.block = int(block)
        self.parent = parent
        self.children = 0


def _chain(parent: bytes, tokens: Sequence[int]) -> bytes:
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.digest()


class Match:
    """One lookup's result: the resident chain's physical blocks plus
    the chain hashes, so a later :meth:`PrefixCache.register` of the
    same prompt resumes the chain instead of re-hashing it."""

    __slots__ = ("blocks", "hashes")

    def __init__(self):
        self.blocks: List[int] = []
        self.hashes: List[bytes] = []


class PrefixCache:
    """Hash-chain index of shared prompt-prefix blocks.

    The engine owns block refcounts; the cache calls ``deref`` (engine
    callback) when an entry is evicted and reports how many blocks that
    actually freed."""

    def __init__(self, block_size: int):
        self.bs = int(block_size)
        self._entries: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._block_arr = None   # lazy cache for reclaimable()
        # bumped on every entry eviction: lookup results (Match) cached
        # across deferred-admission retries are valid only within one
        # epoch — a freed-and-reallocated block must never be aliased
        self.epoch = 0
        self.hits = 0
        self.misses = 0
        self.blocks_shared = 0
        self.evictions = 0

    # -------------------------------------------------------------- queries
    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, prompt_ids: Sequence[int]) -> Match:
        """Longest resident chain of full-block prefixes of the prompt:
        returns a :class:`Match` with the physical block ids in prefix
        order (and the chain hashes, for register() to resume).
        Matched entries (and their ancestors, by construction) are
        LRU-touched."""
        out = Match()
        h = b""
        for i in range(len(prompt_ids) // self.bs):
            h = _chain(h, prompt_ids[i * self.bs:(i + 1) * self.bs])
            ent = self._entries.get(h)
            if ent is None:
                break
            self._entries.move_to_end(h)
            out.blocks.append(ent.block)
            out.hashes.append(h)
        return out

    def resident_blocks(self) -> List[int]:
        return [e.block for e in self._entries.values()]

    def reclaimable(self, block_rc: "np.ndarray") -> int:
        """Blocks held ONLY by the index (refcount 1): freeable on
        demand by :meth:`evict`, so the engine counts them as free
        capacity in its accounting.  Called per tick (occupancy gauge,
        flight records), so it is one vectorized numpy read over a
        lazily rebuilt block-id array — not a Python loop."""
        if self._block_arr is None:
            self._block_arr = np.fromiter(
                (e.block for e in self._entries.values()), np.int64,
                count=len(self._entries))
        if not self._block_arr.size:
            return 0
        return int(np.count_nonzero(block_rc[self._block_arr] == 1))

    # ------------------------------------------------- persistence (ISSUE 15)
    def export_state(self) -> dict:
        """The index as a serializable structure: entries in CHAIN-DEPTH
        order (every parent precedes its children, so import can rebuild
        the child counters in one pass) with hex hashes and the
        exporting engine's physical block ids.  Block ids are only
        meaningful next to the exported block CONTENTS — the engine's
        export bundles both and import remaps ids onto freshly
        allocated blocks."""
        depth: dict = {}
        for h, ent in self._entries.items():
            d, cur = 0, ent.parent
            while cur is not None:
                d += 1
                cur = self._entries[cur].parent
            depth[h] = d
        order = sorted(self._entries.items(), key=lambda kv: depth[kv[0]])
        return {"schema": "paddle_tpu.prefix/v1",
                "block_size": self.bs,
                "entries": [{"hash": h.hex(),
                             "parent": (e.parent.hex()
                                        if e.parent else None),
                             "block": e.block} for h, e in order]}

    def import_state(self, state: dict, alloc: Callable[[], Optional[int]],
                     assign: Callable[[int, int], None]) -> int:
        """Rebuild an exported index into this (empty) cache.

        ``alloc()`` returns a fresh physical block id — the entry's one
        index reference, drawn through the engine's ordinary
        ``_alloc_block`` path — or None when the pool has no room (the
        import stops; index blocks are reclaimable-on-demand, so a
        partial import is just a smaller warm set).  ``assign(old, new)``
        tells the caller to install the exported block ``old``'s KV
        contents into physical block ``new``.  Entries whose parent was
        not imported (capacity cut, or a parent the exporter already
        evicted) are SKIPPED — the chain invariant (no orphan-parent
        entries) survives any truncation.  Returns entries imported."""
        if int(state.get("block_size", -1)) != self.bs:
            raise ValueError(
                f"prefix export block_size {state.get('block_size')} != "
                f"engine block_size {self.bs}")
        n = 0
        for rec in state["entries"]:
            parent = (bytes.fromhex(rec["parent"])
                      if rec.get("parent") else None)
            if parent is not None and parent not in self._entries:
                continue
            h = bytes.fromhex(rec["hash"])
            if h in self._entries:
                continue
            blk = alloc()
            if blk is None:
                break
            ent = _Entry(blk, parent)
            if parent is not None:
                self._entries[parent].children += 1
            self._entries[h] = ent
            self._block_arr = None
            assign(int(rec["block"]), blk)
            n += 1
        return n

    # ------------------------------------------------------------ mutations
    def register(self, prompt_ids: Sequence[int], blocks: Sequence[int],
                 ref: Callable[[int], None],
                 match: Optional[Match] = None) -> int:
        """Walk the prompt's full blocks; add an index entry (taking one
        reference via ``ref``) for each chain position not yet present.
        ``blocks[i]`` is the physical block the caller's table holds at
        column i.  Existing entries are KEPT (their block may differ
        from the caller's — a copy-on-write column keeps the original as
        the shared one).  ``match`` (this prompt's lookup() result)
        supplies the already-computed chain hashes for its depth, so an
        admission hashes each block at most once.  Returns the number of
        new entries."""
        added = 0
        h = b""
        for i in range(min(len(prompt_ids) // self.bs, len(blocks))):
            parent = h
            if match is not None and i < len(match.hashes):
                h = match.hashes[i]
            else:
                h = _chain(h, prompt_ids[i * self.bs:(i + 1) * self.bs])
            ent = self._entries.get(h)
            if ent is not None:
                self._entries.move_to_end(h)
                continue
            ent = _Entry(blocks[i], parent or None)
            if parent:
                par = self._entries.get(parent)
                if par is None:
                    # the parent chain was evicted mid-walk (cannot
                    # happen from the engine's single thread, but keep
                    # the invariant: no orphan-parent entries)
                    break
                par.children += 1
            self._entries[h] = ent
            self._block_arr = None
            ref(ent.block)
            added += 1
        return added

    def evict(self, want_blocks: int, deref: Callable[[int], bool],
              freeable: Optional[Callable[[int], bool]] = None) -> int:
        """Free up to ``want_blocks`` physical blocks by dropping index
        entries, leaf-first in LRU order.  ``deref`` releases one block
        reference and returns True iff the block became free;
        ``freeable`` pre-checks whether dropping the entry's reference
        WOULD free the block — entries whose block is still referenced
        by a running request are SKIPPED, not destroyed: deleting them
        frees no capacity (index-only blocks are already counted as
        reclaimable), it would only throw away a hot prefix.

        One forward pass evicts every current freeable leaf in LRU
        order (O(n), not a rescan per victim); entries whose children
        were all just evicted become leaves for the NEXT pass, so deep
        chains unwind in at most chain-depth passes — and only while
        still short."""
        freed = 0
        progress = True
        while freed < want_blocks and progress:
            progress = False
            for h in list(self._entries.keys()):   # oldest-first
                if freed >= want_blocks:
                    break
                ent = self._entries.get(h)
                if ent is None or ent.children:
                    continue
                if freeable is not None and not freeable(ent.block):
                    continue
                del self._entries[h]
                self._block_arr = None
                self.epoch += 1
                if ent.parent:
                    par = self._entries.get(ent.parent)
                    if par is not None:
                        par.children -= 1
                self.evictions += 1
                progress = True
                if deref(ent.block):
                    freed += 1
        return freed
