"""Vision datasets. Parity: `python/paddle/vision/datasets/`.

No-network environment: MNIST/Cifar load from a local path when present
(`image_path`/`data_file`), else fall back to a deterministic synthetic set of
the same shapes — tests and benchmarks use the synthetic path.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder"]


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 synthetic_size=None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(
                    n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8)
        else:
            n = synthetic_size or (6000 if mode == "train" else 1000)
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            # class-dependent blobs so models can actually learn
            self.images = np.zeros((n, 28, 28), np.uint8)
            for i, lbl in enumerate(self.labels):
                img = rng.rand(28, 28) * 64
                r, c = divmod(int(lbl), 4)
                img[r * 7:(r + 1) * 7 + 7, c * 7:(c + 1) * 7] += 180
                self.images[i] = np.clip(img, 0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img[None]  # CHW
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.transform = transform
        n = synthetic_size or (5000 if mode == "train" else 1000)
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.labels = rng.randint(0, 10, n).astype(np.int64)
        self.images = (rng.rand(n, 32, 32, 3) * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.transpose(2, 0, 1).astype(np.float32) / 255.0
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        raise NotImplementedError("DatasetFolder needs PIL; planned")


class ImageFolder(DatasetFolder):
    pass
