"""Static (preallocated) KV cache for autoregressive decoding.

Parity target: the reference's serving decode path keeps fixed-capacity
KV buffers and writes each new token in place
(`paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu` and
`masked_multihead_attention_kernel.cu` — the write-then-attend decode
step against a preallocated cache).

TPU-native redesign: the eager dense cache concatenates and grows
([B, t, nh, hd] -> [B, t+1, nh, hd]), so every decode position is a NEW
shape and XLA compiles a fresh program per token — fine on GPUs with
cheap JIT-less kernels, pathological under XLA.  A StaticKVCache holds
[B, max_len, nh, hd] buffers and a traced int32 write position: every
step runs the SAME compiled program (`jax.lax.dynamic_update_slice` +
masked attention over the full buffer), so a whole generation costs one
compile.  The over-length attention work is masked dead weight but tiny
at decode batch sizes; the paged Pallas kernel (`ops/pallas_paged.py`)
is the bandwidth-optimal variant of the same idea.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["StaticKVCache"]


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _update_and_attend(cache_k, cache_v, length, q, k, v):
    """Write (k, v) at `length` and attend q against the valid prefix.

    cache_k/v: [B, L, nh, hd]; q/k/v: [B, s, nh, hd]; length: int32 [].
    Returns (new_k, new_v, out[B, s, nh, hd]).  One program for every
    decode step: shapes are static, the position is a traced scalar.
    """
    cache_k = jax.lax.dynamic_update_slice(
        cache_k, k.astype(cache_k.dtype), (0, length, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(
        cache_v, v.astype(cache_v.dtype), (0, length, 0, 0))
    s, hd = q.shape[1], q.shape[3]
    qpos = length + jnp.arange(s)[:, None]            # [s, 1] absolute
    kpos = jnp.arange(cache_k.shape[1])[None, :]      # [1, L]
    mask = kpos <= qpos                               # causal + valid-prefix
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, cache_k) / math.sqrt(hd)
    logits = jnp.where(mask[None, None],
                       logits.astype(jnp.float32), -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, cache_v)
    return cache_k, cache_v, out


class StaticKVCache:
    """Fixed-capacity per-layer KV cache; functional update (returns a
    new cache object, buffers donated to XLA so the update is in-place
    on device).  Registered as a jax pytree so whole decode loops —
    `lax.scan` with the cache as carry — compile into ONE program."""

    def __init__(self, batch: int, max_len: int, num_heads: int,
                 head_dim: int, dtype=jnp.float32):
        self.k = jnp.zeros((batch, max_len, num_heads, head_dim), dtype)
        self.v = jnp.zeros_like(self.k)
        self.length = jnp.zeros((), jnp.int32)

    def update_and_attend(self, q, k, v):
        """q/k/v: jnp [B, s, nh, hd] (new tokens, post-RoPE).  Returns
        (new_cache, out[B, s, nh, hd])."""
        s = q.shape[1]
        if s > self.k.shape[1]:
            raise ValueError(f"prefill of {s} tokens exceeds cache "
                             f"capacity {self.k.shape[1]}")
        if not isinstance(self.k, jax.core.Tracer):
            # eager path: length is concrete — writing past capacity would
            # silently clamp (dynamic_update_slice semantics) and corrupt
            # the last slots, so raise instead
            if not isinstance(self.length, jax.core.Tracer) and \
                    int(self.length) + s > self.k.shape[1]:
                raise ValueError(
                    f"decode past cache capacity: length {int(self.length)}"
                    f" + {s} new > {self.k.shape[1]}")
            new = StaticKVCache.__new__(StaticKVCache)
            new.k, new.v, out = _update_and_attend(
                self.k, self.v, self.length, q, k, v)
            new.length = self.length + jnp.int32(s)
            return new, out
        # traced (inside an outer jit, e.g. a served decode graph): inline
        new = StaticKVCache.__new__(StaticKVCache)
        new.k, new.v, out = _update_and_attend.__wrapped__(
            self.k, self.v, self.length, q, k, v)
        new.length = self.length + jnp.int32(s)
        return new, out


def _cache_flatten(c):
    return (c.k, c.v, c.length), None


def _cache_unflatten(_, children):
    c = StaticKVCache.__new__(StaticKVCache)
    c.k, c.v, c.length = children
    return c


# pytree registration lets whole decode loops carry the cache through
# lax.scan / jit boundaries (one compiled program per generation)
jax.tree_util.register_pytree_node(
    StaticKVCache, _cache_flatten, _cache_unflatten)
