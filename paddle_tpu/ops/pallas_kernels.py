"""Pallas TPU kernel dispatch (flash attention, fused MoE routing).

Role of the reference's hand-fused CUDA kernels
(`phi/kernels/gpu/flash_attn_kernel.cu`, `fusion/gpu/` fused ops): ops XLA
won't fuse optimally get hand-written TPU kernels.  The actual kernels live
in `pallas_flash.py` / `pallas_moe.py`; this module gates applicability and
registers the dispatched ops so the eager tape engine differentiates
through each kernel's custom VJP.

Gating: the kernel path is taken on a real TPU backend with supported
shapes (seqs divisible by their blocks, head_dim in {64, 128, 256}, q
heads a multiple of kv heads).  Key-padding masks ([B, 1, 1, Sk] bool /
[B, Sk]) ride the kernel's kv_mask input; attention dropout runs inside
the kernel (per-block reseeded TPU PRNG).  Anything else — additive
biases, full [Sq, Sk] masks, probability outputs — falls back to the
fused XLA softmax(QK^T)V path, so the same model code runs everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import dispatch as _d, register_op

try:
    from . import pallas_flash
except ImportError:  # pragma: no cover - jax build without pallas
    pallas_flash = None

try:
    from . import pallas_moe
except ImportError:  # pragma: no cover - jax build without pallas
    pallas_moe = None

__all__ = ["flash_attention", "flash_attention_available",
           "as_kv_padding_mask", "moe_fused_available",
           "moe_routing_indices", "moe_dispatch", "moe_combine"]


@functools.cache
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def as_kv_padding_mask(attn_mask, B, Sk):
    """If `attn_mask` (Tensor or array) is unambiguously a BOOLEAN
    key-padding mask — shape [B, 1, Sk] or [B, 1, 1, Sk] (the broadcast
    layouts models build, e.g. BERT's `unsqueeze(mask > 0, [1, 2])`) —
    return it as a [B, Sk] array; else None (caller falls back to XLA).
    Integer masks are NOT accepted: paddle's integer/float attn_mask is
    ADDITIVE (0/-10000 style), the opposite semantics.  A bare 2-D mask
    is also rejected: [B, Sk] is indistinguishable from a per-query
    [Sq, Sk] mask when B == Sq."""
    if attn_mask is None:
        return None
    v = getattr(attn_mask, "_value", attn_mask)
    if v.dtype != jnp.bool_:
        return None
    shape = tuple(v.shape)
    if shape == (B, 1, Sk) or shape == (B, 1, 1, Sk):
        return v.reshape(B, Sk)
    return None


def flash_attention_available(q, k, v, mask=None) -> bool:
    """Shape/backend applicability; `mask` here means a mask the kernel
    CANNOT absorb (callers pass attn_mask only if as_kv_padding_mask
    returned None for it)."""
    if pallas_flash is None or getattr(pallas_flash, "pltpu", None) is None:
        return False
    if mask is not None:
        return False
    if not _on_tpu():
        return False
    return pallas_flash.supported(tuple(q.shape), tuple(k.shape))


if pallas_flash is not None:
    def _fa_op(q, k, v, kv_mask, seed, *, causal, dropout_rate, mask_shape):
        return pallas_flash.flash_attention(
            q, k, v, causal, None, kv_mask, seed, mask_shape, dropout_rate)

    register_op("flash_attention", _fa_op,
                tags=("mxu", "fused", "pallas"))


def flash_attention(q, k, v, causal=False, dropout_p=0.0, kv_mask=None):
    """Pallas flash-attention on [B, S, nh, hd] Tensors; differentiable
    through the kernel's custom VJP (FlashAttention-2 backward kernels).

    kv_mask: optional [B, Sk] 0/1 key-validity Tensor/array (padding);
    dropout_p > 0 applies in-kernel attention dropout (seeded from the
    framework RNG, so paddle.seed reproduces runs)."""
    from ..nn.functional.attention import sdpa_xla
    if not flash_attention_available(q, k, v):
        xla_mask = None
        if kv_mask is not None:
            # keep padding semantics on the fallback: [B, Sk] 0/1 ->
            # [B, 1, 1, Sk] boolean keep-mask broadcast over heads/queries
            mv = getattr(kv_mask, "_value", kv_mask)
            xla_mask = (mv != 0).reshape(mv.shape[0], 1, 1, mv.shape[-1])
        return sdpa_xla(q, k, v, xla_mask, dropout_p, causal, None, True)
    seed = None
    if dropout_p > 0.0:
        from ..framework import random as _random
        seed = jax.random.randint(_random.next_key(), (), 0,
                                  jnp.iinfo(jnp.int32).max, jnp.int32)
    mask_shape = None if kv_mask is None else \
        tuple(getattr(kv_mask, "shape", ()))
    return _d("flash_attention", (q, k, v, kv_mask, seed),
              {"causal": bool(causal), "dropout_rate": float(dropout_p),
               "mask_shape": mask_shape})


# ------------------------------------------------------- fused MoE routing
# The dense (T,E,C) einsum dispatch/combine of the MoE layer replaced by
# the one-pass index-form kernels of `pallas_moe.py` (ISSUE 18).  Unlike
# flash attention these run everywhere pallas imports — interpret mode on
# CPU (row moves, not matmuls, so interpret is not the liability it is
# for attention grids) and Mosaic on TPU.

def moe_fused_available() -> bool:
    """The fused routing data plane can run (pallas imports; on CPU the
    kernels run in interpret mode)."""
    return pallas_moe is not None and \
        getattr(pallas_moe, "pltpu", None) is not None


if pallas_moe is not None:
    register_op(
        "moe_routing_indices",
        lambda eid, slot, keep, *, num_experts, capacity:
            pallas_moe.routing_indices(eid, slot, keep,
                                       num_experts, capacity))
    register_op("moe_dispatch",
                lambda x, inv: pallas_moe.moe_dispatch(x, inv),
                tags=("fused", "pallas"))
    register_op("moe_combine",
                lambda rows, w, flat: pallas_moe.moe_combine(rows, w, flat),
                tags=("fused", "pallas"))


def moe_routing_indices(eid, slot, keep, num_experts, capacity):
    """Index plumbing for the fused MoE path: flat destination slot per
    (token, choice) and the inverse slot->token map.  Integer outputs —
    the routing gradient rides the combine weights, not these."""
    return _d("moe_routing_indices", (eid, slot, keep),
              {"num_experts": int(num_experts), "capacity": int(capacity)})


def moe_dispatch(x, inv):
    """Pack token rows [T, M] into flat expert buffers [E*C, M] by the
    inverse slot map; differentiable through the kernel's custom VJP
    (scatter-add transpose)."""
    return _d("moe_dispatch", (x, inv), {})


def moe_combine(expert_rows, w, flat):
    """Mix expert output rows [E*C, M] back to tokens [T, M] with the
    combine weights w [T, k]; differentiable in both expert_rows and w."""
    return _d("moe_combine", (expert_rows, w, flat), {})
