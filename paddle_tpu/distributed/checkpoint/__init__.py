"""Distributed (sharded) checkpoint: save/load with reshard-on-load.

Parity: `python/paddle/distributed/checkpoint/` — save_state_dict
(`save_state_dict.py:104`), load_state_dict (`load_state_dict.py:377`),
Metadata (`metadata.py:20`).
"""

from .load_state_dict import load_metadata, load_state_dict
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata
from .save_state_dict import save_state_dict, wait_async_save
from .utils import flatten_state_dict, unflatten_state_dict

__all__ = [
    "save_state_dict", "load_state_dict", "load_metadata", "wait_async_save",
    "Metadata", "LocalTensorMetadata", "LocalTensorIndex",
    "flatten_state_dict", "unflatten_state_dict",
]
