"""Dtype system.

Analogue of the reference's ``phi::DataType`` (`paddle/phi/common/data_type.h`)
exposed in Python as ``paddle.float32`` etc.  We alias JAX/NumPy dtypes so that
tensors interoperate with jax.numpy directly, and keep paddle's names and
default-dtype machinery (`python/paddle/framework/framework.py` set_default_dtype).
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp
import numpy as np

__all__ = [
    "bfloat16", "float16", "float32", "float64",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "bool_", "complex64", "complex128",
    "set_default_dtype", "get_default_dtype", "convert_dtype",
    "is_floating_point_dtype", "is_integer_dtype", "promote_types",
    "finfo", "iinfo",
]

bfloat16 = jnp.bfloat16
float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float16": float16, "fp16": float16, "half": float16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64, "int": int32,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool_, "complex64": complex64, "complex128": complex128,
}

_state = threading.local()


_X64_DOWNMAP = {
    np.dtype(np.int64): np.dtype(np.int32),
    np.dtype(np.uint64): np.dtype(np.uint32),
    np.dtype(np.float64): np.dtype(np.float32),
    np.dtype(np.complex128): np.dtype(np.complex64),
}


def convert_dtype(dtype) -> np.dtype:
    """Normalize any dtype spec (str, np/jnp dtype, paddle name) to np.dtype.

    TPU-native policy: with JAX in default x32 mode, 64-bit integer requests
    canonicalize to 32-bit (the reference defaults indices to int64 because
    CUDA handles it; on TPU int32 is the native lane width).
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _ALIASES:
            raise TypeError(f"Unknown dtype {dtype!r}")
        d = np.dtype(_ALIASES[dtype])
    else:
        d = np.dtype(dtype)
    import jax
    if not jax.config.jax_enable_x64 and d in _X64_DOWNMAP:
        return _X64_DOWNMAP[d]
    return d


def set_default_dtype(d) -> None:
    d = convert_dtype(d)
    if d not in (np.dtype(float16), np.dtype(bfloat16), np.dtype(float32),
                 np.dtype(float64)):
        raise TypeError(f"Default dtype must be a float type, got {d}")
    _state.default_dtype = d


def get_default_dtype() -> np.dtype:
    return getattr(_state, "default_dtype", np.dtype(np.float32))


@contextlib.contextmanager
def default_dtype_guard(d):
    old = get_default_dtype()
    set_default_dtype(d)
    try:
        yield
    finally:
        _state.default_dtype = old


def canonical_index_dtype() -> np.dtype:
    """Native index dtype: int32 in x32 mode (TPU lane width), else int64."""
    import jax
    return np.dtype(np.int64) if jax.config.jax_enable_x64 else np.dtype(np.int32)


def is_floating_point_dtype(dtype) -> bool:
    return np.issubdtype(convert_dtype(dtype), np.floating) or \
        convert_dtype(dtype) == np.dtype(bfloat16)


def is_integer_dtype(dtype) -> bool:
    return np.issubdtype(convert_dtype(dtype), np.integer)


def promote_types(a, b) -> np.dtype:
    return np.dtype(jnp.promote_types(convert_dtype(a), convert_dtype(b)))


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return jnp.iinfo(convert_dtype(dtype))
