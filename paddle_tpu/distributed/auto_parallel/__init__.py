from .api import (dtensor_from_fn, reshard, shard_layer, shard_optimizer,  # noqa: F401
                  shard_tensor, to_static, unshard_dtensor)
from .placement import Partial, Placement, Replicate, Shard  # noqa: F401
from .process_mesh import ProcessMesh  # noqa: F401
