"""Continuous-batching serving loop over the paged KV cache.

Role of the reference's production decode service: the paged cache-KV
branch of `fused_multi_transformer_op.cu.h` (+ `block_multi_head_
attention_kernel.cu`) driven by a request scheduler behind
`analysis_predictor.h:100`.  TPU-native shape:

* ONE compiled decode step for the whole engine, regardless of batch
  mix: fixed `max_batch` slots, a shared physical block pool per layer,
  per-slot block tables and seq_lens as device inputs.  Admissions,
  evictions, and block allocation are HOST-side bookkeeping between
  compiled steps (exactly where serving schedulers live), so joining or
  finishing a sequence never recompiles anything.
* Admission runs a compiled prefill program (cached per padded prompt
  bucket) that writes the prompt's K/V into the new slot's blocks
  through the SAME pools and returns the last real token's logits.
* Free slots ride through the decode program as seq_len-0 rows: their
  writes land in the reserved pad block 0 and their attention output is
  ignored, so occupancy changes cost nothing.
* Sampling happens ON DEVICE inside the compiled k-step tick (the seat
  of the reference's fused top-p path in
  `fused_multi_transformer_op.cu.h`): per-slot temperature/top-k/top-p/
  do_sample masks and PRNG seeds are device INPUTS, so changing the
  sampling mix never recompiles anything and sampled requests amortize
  the host round trip over the same k steps greedy ones do.  The
  host-side per-row sampler survives behind
  ``FLAGS_serving_device_sampling=0`` (it demotes ticks to k=1).
* The tick loop double-buffers (``FLAGS_serving_overlap``): tick t+1's
  compiled step is dispatched — feeding tick t's on-device last-token
  handle straight back in — BEFORE tick t is harvested, so device
  compute overlaps host detokenize/bookkeeping.  JAX async dispatch
  makes this a reordering plus one in-flight handle, not a thread; an
  EOS discovered at harvest simply wastes the already-dispatched step
  (the block-budget clamp keeps the overrun inside the admission
  reservation).

Block accounting reserves the worst case (prompt + max_new_tokens) at
admission, so a running sequence can never hit pool exhaustion
mid-flight (no preemption needed — the reference scheduler's "no-evict"
configuration).

Scale-out (ISSUE 9): ``FLAGS_serving_tp_degree`` rebuilds every program
as a ``shard_map`` over a 'tp' mesh axis — weights column-parallel, KV
pools sharded along the head axis, scheduler state replicated (the
rank-0 broadcast) — with decode streams BIT-identical to degree 1
(`inference/tp.py` has the no-split-reductions layout contract).
``FLAGS_serving_prefix_cache`` adds refcounted prompt-prefix reuse over
the block table: a resident prefix is a pointer copy at admission, the
suffix runs a chunked prefill program, shared blocks copy-on-write when
the last prompt token must be recomputed, and index eviction under pool
pressure frees only orphaned blocks (`inference/prefix_cache.py`).

Speculative + quantized serving (ISSUE 10, extended by ISSUE 13):
``FLAGS_serving_spec_decode`` adds the spec tick — draft tokens for
every slot judged by the target in a single `PagedChunkView` chunk
verify forward, per-slot accept masks emitting 1..k tokens LOSSLESSLY
(greedy bit-identical to the plain engine; seeded sampling corrected
by rejection sampling — `inference/speculative.py`).  The proposal
source is ``FLAGS_serving_spec_draft``: ``model`` runs a draft model's
k-step scan over its own pools behind the SAME block table (prefix
sharing, CoW and refcounts cover both models); ``ngram`` proposes from
a per-request host-side suffix table (`inference/drafting.py`) and
feeds the proposals in as DEVICE INPUTS — no draft model, pools, or
prefill at all.  Eligibility is PER SLOT: each slot carries an emit
cap ``min(k, remaining budget)`` into the program, so a short-budget
slot no longer demotes the whole tick to the plain path — it just
emits up to its cap (budget accounting refunds per slot at harvest).
``FLAGS_serving_spec_adaptive`` steps k through the
``FLAGS_serving_spec_k_ladder`` rungs at tick boundaries, driven by
the live acceptance-rate EWMA; every rung's program is enumerated into
the warmup grid, so adaptation never compiles under traffic.
``FLAGS_serving_quant=int8|fp8`` snapshots the matmul weights
per-channel at construction and dequantizes in-trace
(`inference/quant.py`): ~4x less fp32 weight memory on device, bounded
logit deviation (per-mode budget), bit-exact across TP degrees.

Continuous batching (ISSUE 11): ``FLAGS_serving_prefill_chunk`` makes
prefill INCREMENTAL — an arriving prompt of any length is absorbed as
bounded-size chunks of the suffix-prefill program (one per ladder
bucket, ``start``/length traced scalars — zero new program shapes),
interleaved between decode ticks by a per-tick scheduler that budgets
each boundary as "one decode tick + up to
``FLAGS_serving_prefill_chunks_per_tick`` chunk(s)".  Running streams'
inter-token gap is bounded by one chunk + one tick regardless of
arriving prompt length, and the chunked streams are BIT-identical to
monolithic prefill (same `PagedChunkView` writes, same offset causal
mask).  A mid-prefill slot keeps its table row SHADOWED on the request
(the engine row stays zero) so overlapping decode ticks stay inert for
it.  The scheduler is also SLO-aware: ``FLAGS_serving_slo_shed``
rejects (reason=slo_shed) the newest lowest-priority waiting requests
while the live TTFT/TPOT p99 sketches breach their targets and the
queue is past ``FLAGS_serving_shed_queue_depth``; `Request(priority=)`
orders admission.  ``FLAGS_serving_http_port`` exposes the engine as a
minimal streaming frontend: ``POST /generate`` answers a Server-Sent
Events token stream (`observability/http.py`), with client disconnect
and timeout propagating to `Request.cancel()` -> slot eviction and
block release at the next boundary.

Crash-only serving (ISSUE 15): the tick loop is supervised.  A
dispatch/harvest exception no longer kills ``run()``/``serve_forever``
— transient RuntimeError dispatches retry in place
(``FLAGS_serving_dispatch_retries``, the shared io_retry backoff), an
admission-stage failure strikes the REQUEST (two strikes — its program
raised, or its prefill logits went non-finite under the flight-recorder
watchdog — and it is rejected ``reason=poisoned`` instead of re-crashing
every boundary), and an unattributable tick failure evicts exactly the
implicated slots ``outcome=error`` with every block released through
the single ``_alloc/_ref/_release_block`` path (blocksan stays green)
while the other slots' streams continue bit-identically.  A harvest
that never materializes (hung ``block_until_ready``) is caught by the
tick watchdog (``FLAGS_serving_tick_timeout_s``) and failed like any
other tick error.  ``drain()`` (SIGTERM under ``serve_forever``, or
``POST /drain``) is the graceful half: admission closes (healthz 503
``draining``), in-flight requests finish up to
``FLAGS_serving_drain_timeout_s``, the waiting queue is cancelled with
SSE error frames, the block ledger is blocksan-verified empty-running,
and the prefix cache exports its hash-chain index + block contents
through the PR 5 atomic-manifest machinery into
``FLAGS_serving_prefix_export_dir`` — which a NEW engine imports at
construction (corrupt exports skipped with a counter, never loaded), so
restart-to-first-token on a hot system prompt is warm-cache (+
warm-compile via the persistent compilation cache).

Cold start (ISSUE 7): the set of programs the engine can EVER dispatch
is small and enumerable — one tick program per {steps_per_tick, 1-step
tail} (greedy and sampled share it: sampling params are device inputs
and ``lax.cond`` compiles both branches), the host-sampling k=1 decode
program (``FLAGS_serving_device_sampling`` is read at dispatch, so both
variants warm), and one prefill program per pad bucket.  The pad buckets come from ONE ladder
(``FLAGS_serving_pad_buckets`` or the power-of-two default, clamped to
the block table) shared by admission padding, worst-case block
accounting, and :meth:`ServingEngine.warmup`, which walks exactly that
grid — AOT ``.lower().compile()`` where it works, an inert dummy-input
execution otherwise — so with ``FLAGS_serving_warmup=1`` the compile
tracker records ZERO events once ``run()`` admits traffic.
"""

from __future__ import annotations

import json
import math
import os
import threading
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags as _flags
from ..framework.tensor import Tensor
from ..testing import chaos as _chaos
from ..testing import jaxsan as _jaxsan
from ..observability import compile_tracker as _compile
from ..observability import export as _export
from ..observability import xray as _xray
from ..observability import flight_recorder as _flight
from ..observability import metrics as _metrics
from ..observability import quantiles as _quantiles
from . import quant as _squant
from .prefix_cache import PrefixCache

__all__ = ["Request", "ServingEngine", "TickTimeout", "NonFiniteLogits"]

_M_ADMISSIONS = _metrics.counter(
    "serving.admissions", "requests admitted into a decode slot")
_M_REJECTIONS = _metrics.counter(
    "serving.rejections",
    "requests rejected or stalled, by reason: over_context (prompt + "
    "budget exceed max_context), capacity (worst-case blocks exceed the "
    "whole pool — can never fit), pool_exhausted (admission deferred "
    "because the pool is currently drained; counted once per request), "
    "error (admission failed mid-flight)")
_M_TICKS = _metrics.counter(
    "serving.ticks", "scheduler ticks that ran a compiled decode step")
_M_TOKENS = _metrics.counter(
    "serving.tokens_out", "tokens emitted to requests")
_M_TICK_S = _metrics.histogram(
    "serving.tick_seconds", "wall time of one decode tick (k compiled "
    "steps + host scheduling)")
_M_POOL = _metrics.gauge(
    "serving.pool_occupancy", "fraction of physical KV blocks in use")
_M_SLOTS = _metrics.gauge(
    "serving.slot_occupancy", "fraction of batch slots holding a request")
_M_TPS = _metrics.gauge(
    "serving.tokens_per_sec", "decode tokens/sec over the last tick")
_M_SAMPLED = _metrics.counter(
    "serving.sampled_tokens", "tokens drawn by the sampler (device or "
    "host path) rather than argmax")
_M_OVERLAP = _metrics.counter(
    "serving.overlap_dispatches", "ticks dispatched before the previous "
    "tick was harvested (double-buffered fast path)")
_M_PREFIX_HITS = _metrics.counter(
    "serving.prefix_hits", "admissions whose prompt prefix was resident "
    "in the shared-block index (prefill skipped for those blocks)")
_M_PREFIX_MISSES = _metrics.counter(
    "serving.prefix_misses", "admissions that found no resident prefix "
    "(full prefill ran); counted only with the prefix cache enabled")
_M_PREFIX_SHARED = _metrics.counter(
    "serving.prefix_blocks_shared", "physical KV blocks reused from the "
    "prefix index instead of recomputed (incl. copy-on-write sources)")
_M_SPEC_PROPOSED = _metrics.counter(
    "serving.spec_proposed_tokens", "draft tokens proposed to the "
    "speculative verify forward (k per live slot per spec tick); the "
    "acceptance rate is spec_accepted_tokens / spec_proposed_tokens")
_M_SPEC_ACCEPTED = _metrics.counter(
    "serving.spec_accepted_tokens", "draft tokens accepted by the "
    "verify forward (greedy argmax match or rejection-sampling accept)")
_M_SPEC_INELIGIBLE = _metrics.counter(
    "serving.spec_ineligible_slots", "active slots dispatched into a "
    "spec tick with a per-slot emit cap BELOW the tick's k (remaining "
    "budget under k): they ride the same program capped instead of "
    "demoting the whole tick to the plain path")
_M_SPEC_K = _metrics.gauge(
    "serving.spec_k_now", "speculative k of the most recent spec "
    "dispatch (steps through FLAGS_serving_spec_k_ladder when "
    "FLAGS_serving_spec_adaptive drives it)")
_M_SPEC_SLOT_ACC = _metrics.gauge(
    "serving.spec_slot_accept_rate", "per-slot lifetime draft "
    "acceptance rate of the slot's CURRENT request (labelled slot=i; "
    "the adaptive-k controller consumes the engine-wide EWMA of the "
    "same signal)")
_M_PREFILL_CHUNKS = _metrics.counter(
    "serving.prefill_chunks", "chunk prefill programs dispatched by the "
    "continuous-batching scheduler (FLAGS_serving_prefill_chunk > 0: an "
    "arriving prompt is absorbed in bounded chunks between decode ticks "
    "instead of one monolithic prefill)")
_M_SLO_SHEDS = _metrics.counter(
    "serving.slo_sheds", "waiting requests rejected by SLO-aware load "
    "shedding (FLAGS_serving_slo_shed: live TTFT/TPOT p99 over target "
    "AND queue depth over the watermark); every shed also counts on "
    "serving.rejections{reason=slo_shed}")
_M_TICK_ERRORS = _metrics.counter(
    "serving.tick_errors", "tick-loop failures absorbed by the crash-"
    "only guard (ISSUE 15): a dispatch/harvest exception or a tick-"
    "watchdog timeout that evicted the implicated slots (outcome="
    "error) or struck an admission-stage request instead of killing "
    "run()/serve_forever")
_M_POISONED = _metrics.counter(
    "serving.poisoned_requests", "requests quarantined after two "
    "admission-stage strikes (program raised, or prefill logits non-"
    "finite under the NaN watchdog): rejected reason=poisoned instead "
    "of re-crashing every scheduler boundary")
_M_DISPATCH_RETRIES = _metrics.counter(
    "serving.dispatch_retries", "transient serving-program dispatch "
    "failures retried in place (FLAGS_serving_dispatch_retries, "
    "labelled site=); only exhausted retries reach the tick guard")
_M_PREFIX_IMPORT = _metrics.counter(
    "serving.prefix_import_blocks", "physical KV blocks restored from "
    "a drain-time prefix-cache export at engine construction "
    "(FLAGS_serving_prefix_export_dir): each was re-pinned through the "
    "ordinary _alloc/_ref path and is index-evictable under pressure")
_M_PREFIX_IMPORT_SKIP = _metrics.counter(
    "serving.prefix_import_skipped_corrupt", "prefix-cache export "
    "versions SKIPPED at import, by reason=corrupt (manifest/sentinel/"
    "sha256 validation failed — truncation or bit rot) | mismatch "
    "(index readable but from an incompatible engine: different "
    "model/pool geometry or quant mode) | unreadable (payload failed "
    "to parse despite a valid manifest); a skipped version is never "
    "loaded — import falls back to the next older one")

# --- request lifecycle tracing (ISSUE 6): every request's
# enqueue -> admit (queue wait) -> prefill -> first token -> per-tick
# decode -> finish timeline feeds streaming quantile sketches, so
# p50/p90/p99 TTFT/TPOT are readable at any moment from stats(), the
# registry snapshot, or the /metrics scrape — O(1) memory, gated with
# everything else on FLAGS_enable_metrics (off = no timestamps taken).
_M_TTFT = _metrics.quantile(
    "serving.ttft_seconds", "time to first token: request enqueue to the "
    "first output token materialized on the host (queue wait + prefill)")
_M_TPOT = _metrics.quantile(
    "serving.tpot_seconds", "inter-token latency (TPOT): per decoded "
    "token, the harvest-to-harvest gap divided by the tokens it yielded")
_M_E2E = _metrics.quantile(
    "serving.e2e_seconds", "end-to-end request latency: enqueue to the "
    "token that finished the request")
_M_QWAIT = _metrics.quantile(
    "serving.queue_wait_seconds", "enqueue to admission start (deferred "
    "requests accumulate real pool-exhausted wait here)")
_M_SLO = _metrics.counter(
    "serving.slo_violations", "latency SLO breaches, by metric=ttft "
    "(per request, against FLAGS_serving_ttft_slo_ms) or metric=tpot "
    "(per token, against FLAGS_serving_tpot_slo_ms); 0-valued flags "
    "disable the checks")
_M_QUEUE_DEPTH = _metrics.gauge(
    "serving.queue_depth", "requests inside the engine (admission queue "
    "+ running slots)")
_M_RUNNING = _metrics.gauge(
    "serving.running", "batch slots currently holding a request")
_M_WAITING = _metrics.gauge(
    "serving.waiting", "requests queued for admission")
_M_OUTCOMES = _metrics.counter(
    "serving.request_outcomes", "terminal request outcomes, by outcome= "
    "finished | cancelled | error | poisoned | drained | slo_shed | "
    "rejected:<reason>; the fleet federation sums these per replica and "
    "the SLO burn-rate monitor reads error|poisoned as budget burn")


class TickTimeout(RuntimeError):
    """The harvest of a compiled tick did not materialize within
    ``FLAGS_serving_tick_timeout_s`` — a hung device program.  Raised
    inside the tick loop and absorbed by the crash-only guard (the
    implicated slots are evicted ``outcome=error``)."""


class NonFiniteLogits(RuntimeError):
    """A request's host-visible logits went NaN/Inf (flight-recorder
    watchdog probe).  At admission this is a poison strike: the request
    retries once from the back of the queue, then is quarantined
    ``reason=poisoned``."""


class Request:
    """One generation request; results accumulate in `output_ids`."""

    _counter = 0

    def __init__(self, prompt_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 seed: Optional[int] = None, priority: int = 0,
                 trace_id: Optional[str] = None,
                 parent_span: Optional[str] = None):
        Request._counter += 1
        self.rid = Request._counter
        self.prompt_ids = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.do_sample = do_sample
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        # one integer seed drives BOTH samplers: the host RandomState
        # (prefill's first token + the FLAGS_serving_device_sampling=0
        # fallback) and the per-slot device PRNG key (decode tokens are
        # drawn from fold_in(key(seed), token_position), so a rerun with
        # the same seed reproduces the stream regardless of tick sizes)
        self.seed = int(seed) if seed is not None else self.rid
        self._rng = np.random.RandomState(self.seed)
        self.output_ids: List[int] = []
        self.done = False
        self.slot: Optional[int] = None
        # scheduler knobs (ISSUE 11): higher priority admits first among
        # waiting requests (FIFO within a priority); cancel() asks the
        # engine to drop the request at its next scheduler boundary
        # (waiting -> dropped, mid-prefill -> aborted, running -> slot
        # evicted + blocks released) — a bare bool store, so the serve
        # endpoint's handler threads may call it without a lock
        self.priority = int(priority)
        self.cancelled = False
        self.shed = False             # rejected by SLO load shedding
        # terminal outcome for the SSE frontend (ISSUE 15): "finished",
        # "cancelled", or an engine-ended reason ("error", "poisoned",
        # "slo_shed", "drained", ...) that becomes the stream's terminal
        # `event: error` frame; None while the request is live
        self.outcome: Optional[str] = None
        # admission-stage poison strikes (program raised / logits went
        # non-finite); at _POISON_STRIKES the request is quarantined
        self._strikes = 0
        # chunked-prefill admission state (engine-owned; the table row
        # lives HERE — shadowing self.tables — until the last chunk
        # lands, so in-flight decode ticks see an all-zero row and
        # route their seq_len-0 writes to the pad block)
        self._prefilling = False
        self._prefill_chunks = 0
        self._chunk_row = None        # np [nb_per_seq] shadow table row
        self._chunk_off = 0           # prompt tokens written so far
        self._chunk_t_admit = None
        # token stream listener (the SSE endpoint): harvest puts each
        # emitted token id, terminal states put None
        self._stream_q = None
        # lifecycle trace timestamps (perf_counter; stamped only while
        # FLAGS_enable_metrics is on — None means "not traced")
        self._t_enqueue: Optional[float] = None
        # always-on twin of _t_enqueue for the fleet router's TTFT
        # evidence (/healthz) — NOT part of the tracing surface
        self._t_enqueue_ev: Optional[float] = None
        self._t_admit: Optional[float] = None
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._ticks = 0
        self._prefix_blocks = 0   # shared blocks reused at admission
        self._spec_proposed = 0   # draft tokens proposed for this request
        self._spec_accepted = 0   # ...and accepted by the verify forward
        self._drafter = None      # per-request n-gram table (spec_draft=
                                  # ngram; created lazily at first spec
                                  # dispatch)
        # distributed trace context (ISSUE 17): minted by the fleet
        # router (X-Graft-Trace header) or the caller; threaded into
        # every lifecycle / flight record this request produces so the
        # fleet-trace merge can follow it across processes
        self.trace_id: Optional[str] = trace_id
        self.parent_span: Optional[str] = parent_span
        self.trace: Optional[dict] = None   # final record, set at finish

    def _trace_ctx(self) -> dict:
        """``{trace_id, parent_span}`` when traced, else ``{}`` — the
        splat that tags a lifecycle record with this request's trace."""
        if self.trace_id is None:
            return {}
        ctx = {"trace_id": self.trace_id}
        if self.parent_span is not None:
            ctx["parent_span"] = self.parent_span
        return ctx

    def cancel(self) -> None:
        """Ask the engine to drop this request at its next scheduler
        boundary.  Safe from any thread (the serve endpoint calls it on
        client disconnect / request timeout)."""
        self.cancelled = True

    def _stream_push(self, tok: Optional[int]) -> None:
        q = self._stream_q
        if q is not None:
            q.put(tok)

    def _sample(self, logits_row: np.ndarray) -> int:
        if not self.do_sample:
            return int(np.argmax(logits_row))
        from ..models.generation import _process_logits
        filtered = np.asarray(_process_logits(
            jnp.asarray(logits_row, jnp.float32)[None],
            self.temperature, self.top_k, self.top_p))[0]
        p = np.exp(filtered - filtered.max())
        p = p / p.sum()
        return int(self._rng.choice(len(p), p=p))


class _PendingTick:
    """One compiled decode tick in flight.  `toks` ([B, k] int32) is a
    device handle the host has not blocked on — harvest materializes it;
    a second dispatch may slice its last column first (overlap).

    A SPECULATIVE tick (``spec``) additionally carries the per-slot
    emitted counts / accepted-draft counts and the new seq_lens /
    last-token device handles an overlapped next spec tick chains on
    (the host cannot know the accepted length until harvest)."""

    __slots__ = ("active", "k", "toks", "logits", "reqs", "t0",
                 "device_sampling", "overlapped", "step_no", "san",
                 "spec", "counts", "accepts", "new_lens", "new_last",
                 "chunks", "kcap", "ph_sched", "ph_chunk", "ph_dispatch")

    def __init__(self, active, k, toks, logits, reqs, t0,
                 device_sampling, step_no, san=None):
        self.active = active
        self.k = k
        self.toks = toks
        self.logits = logits
        self.reqs = reqs
        self.t0 = t0
        self.device_sampling = device_sampling
        self.overlapped = False
        self.step_no = step_no
        self.san = san
        self.spec = False
        self.counts = None
        self.accepts = None
        self.new_lens = None
        self.new_last = None
        self.chunks = 0     # prefill chunks run at this tick's boundary
        self.kcap = None    # per-slot emit caps of a spec dispatch
        # per-tick phase breakdown (ISSUE 14): host seconds spent in
        # boundary scheduling / chunk-prefill dispatch / tick dispatch,
        # stamped at dispatch time; harvest/emit measured at harvest
        self.ph_sched = 0.0
        self.ph_chunk = 0.0
        self.ph_dispatch = 0.0


def _next_tokens(logits, do_sample, temperature, top_k, top_p, seeds,
                 tok_pos, j):
    """One decode step's token choice over [B, V] logits: greedy rows
    argmax, sampling rows draw from fold_in(key(seed), position) over
    the per-row filtered logits; an all-greedy mix skips the [B, V]
    sort at run time.  Shared verbatim by the degree-1 and TP tick
    bodies so the choice math is one definition."""
    from ..models.generation import _process_logits_rows
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def drawn():
        filtered = _process_logits_rows(
            logits.astype(jnp.float32), temperature, top_k, top_p)
        keys = jax.vmap(lambda s, p: jax.random.fold_in(
            jax.random.key(s), p + j))(seeds, tok_pos)
        samp = jax.vmap(jax.random.categorical)(
            keys, filtered).astype(jnp.int32)
        return jnp.where(do_sample, samp, greedy)

    return jax.lax.cond(jnp.any(do_sample), drawn, lambda: greedy)


class _RetryCounter:
    """io_retry counter adapter: every transient-dispatch retry counts
    on the engine AND the process registry."""

    __slots__ = ("_engine",)

    def __init__(self, engine):
        self._engine = engine

    def inc(self, **labels):
        self._engine.dispatch_retries += 1
        _M_DISPATCH_RETRIES.inc(**labels)


def _bucket(n: int, minimum: int) -> int:
    b = max(minimum, 1)
    while b < n:
        b *= 2
    return b


class ServingEngine:
    """Continuous batching over a model with `forward_with_cache` +
    paged caches (GPT/Llama families).

    engine = ServingEngine(model, max_batch=4, max_context=512)
    engine.add_request(Request([1, 2, 3], max_new_tokens=16))
    finished = engine.run()          # or engine.step() incrementally
    """

    def __init__(self, model, max_batch: int = 4,
                 max_context: Optional[int] = None, block_size: int = 64,
                 num_blocks: Optional[int] = None,
                 steps_per_tick: int = 1,
                 pad_buckets=None, tp_degree: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 draft_model=None, spec_decode: Optional[bool] = None,
                 spec_k: Optional[int] = None,
                 spec_draft: Optional[str] = None,
                 spec_adaptive: Optional[bool] = None,
                 spec_k_ladder=None,
                 quant: Optional[str] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_export_dir: Optional[str] = None):
        # steps_per_tick > 1 compiles a k-step lax.scan per tick so one
        # host round trip harvests k tokens per slot (the tunnel's RTT
        # otherwise caps serving at ~1/RTT steps); admissions join at
        # tick boundaries — the standard iteration-level scheduling
        # granularity tradeoff.  Sampling runs on device inside the same
        # scan (per-slot params + PRNG seeds are inputs), so sampled
        # requests keep the full k too.
        self.model = model
        cfg = model.cfg
        self.B = max_batch
        self.bs = block_size
        self.max_context = int(max_context or cfg.max_seq_len)
        self.nb_per_seq = math.ceil(self.max_context / block_size)
        if num_blocks is None:
            num_blocks = max_batch * self.nb_per_seq
        self.num_blocks = num_blocks
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        self.nh, self.hd = nh, hd
        self._sd = model.state_dict()
        self._keys = sorted(self._sd)
        dtype = self._sd[self._keys[0]]._value.dtype
        # --- weight-only quantization (ISSUE 10): snapshot the matmul
        # weights per-channel int8 at construction; every program takes
        # the int8 payload as input and dequantizes IN-trace right
        # before binding (`_bind_params`), so device weight residency is
        # int8.  Like TP, quant implies snapshot semantics: later
        # mutations of the live model tensors do not reach the engine.
        qmode = quant if quant is not None \
            else _flags.get_flag("serving_quant")
        self.quant_mode = str(qmode or "")
        if self.quant_mode and self.quant_mode not in _squant.MODES:
            # checked HERE so the TP plan path fails as loudly as the
            # degree-1 snapshot path (a typo'd mode must not silently
            # serve int8 accuracy)
            raise ValueError(
                f"FLAGS_serving_quant supports {_squant.MODES}; "
                f"got {self.quant_mode!r}")
        self._qw = None
        self._quant_stats = None
        # --- tensor-parallel decode (ISSUE 9): shard the programs over a
        # 'tp' mesh axis — weights column-parallel (heads/FFN/vocab), KV
        # pools along the head axis; the host scheduler stays rank-0 and
        # every replicated input (tables, seq_lens, sampling params) is
        # the broadcast admission.  Degree 1 (the default) is bit-for-bit
        # today's single-program path; >1 snapshots the weights into the
        # sharded layout at construction (live _sd re-binds per dispatch
        # stay a degree-1-only feature).
        self.tp = int(tp_degree if tp_degree is not None
                      else _flags.get_flag("serving_tp_degree"))
        if self.tp < 1:
            raise ValueError(f"serving_tp_degree must be >= 1: {self.tp}")
        self._tp_mesh = None
        self._tp_params = None
        self._tp_specs = None
        self._tp_meta = None
        if self.tp > 1:
            from ..distributed import mesh as _mesh_mod
            from . import tp as _tp
            devs = list(jax.devices())
            if len(devs) < self.tp:
                raise ValueError(
                    f"serving_tp_degree={self.tp} needs {self.tp} local "
                    f"devices; jax sees {len(devs)}")
            self._tp_mesh = _mesh_mod.build_mesh(
                {_tp.AXIS: self.tp}, devices=devs[:self.tp])
            plan = _tp.build_plan(model, self.tp)
            if self.quant_mode:
                # quantize BEFORE sharding: per-channel scales keep
                # their reduced axis, so each rank's (int8, scale)
                # shard dequantizes to an exact slice of the full
                # dequantized matrix — quant x TP stays bit-parity
                _squant.quantize_plan(plan, self.quant_mode)
                self._quant_stats = _squant.plan_stats(plan)
            self._tp_params = _tp.shard_plan(plan, self._tp_mesh)
            self._tp_specs = plan.specs
            self._tp_meta = plan.meta
        elif self.quant_mode:
            self._qw = _squant.snapshot(
                self._keys, [self._sd[k]._value for k in self._keys],
                self.quant_mode)
            self._quant_stats = self._qw.stats()
        # physical pools per layer; block 0 is the pad/scratch block
        # (TP: sharded along the head axis so each rank holds its heads'
        # blocks — the KV-memory scale-out)
        def _pool():
            z = jnp.zeros((nh, num_blocks + 1, block_size, hd), dtype)
            if self._tp_mesh is None:
                return z
            from jax.sharding import NamedSharding
            from . import tp as _tp
            return jax.device_put(
                z, NamedSharding(self._tp_mesh, _tp.pool_spec()))
        self.pools = [(_pool(), _pool()) for _ in range(cfg.num_layers)]
        # --- speculative decoding (ISSUE 10): the draft model proposes
        # spec_k tokens per slot inside one compiled program; the target
        # judges all k proposals in one chunk verify forward
        # (inference/speculative.py has the losslessness contract).  The
        # draft keeps its OWN paged pools indexed by the SAME block
        # table — one allocator/refcount/prefix path covers both models.
        spec = (spec_decode if spec_decode is not None
                else _flags.get_flag("serving_spec_decode"))
        self.spec = bool(spec)
        self.spec_k = int(spec_k if spec_k is not None
                          else _flags.get_flag("serving_spec_k"))
        kind = (spec_draft if spec_draft is not None
                else _flags.get_flag("serving_spec_draft"))
        self.spec_kind = str(kind or "model")
        if self.spec_kind not in ("model", "ngram"):
            raise ValueError(
                "FLAGS_serving_spec_draft supports 'model' or 'ngram'; "
                f"got {self.spec_kind!r}")
        adaptive = (spec_adaptive if spec_adaptive is not None
                    else _flags.get_flag("serving_spec_adaptive"))
        self.spec_adaptive = bool(adaptive)
        # model-draft state only exists for spec_draft='model'
        self.spec_model = self.spec and self.spec_kind == "model"
        self.draft = draft_model if self.spec_model else None
        self.dpools = None
        self._dsd = None
        self._dkeys = None
        self._dqw = None
        self._tp_draft_vals = None
        self._spec_fns = {}       # model-draft spec tick, per ladder k
        self._spec_hd_fns = {}    # host-draft (ngram) twin, per ladder k
        self.spec_ticks = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_ineligible_slots = 0
        self.spec_k_switches = 0
        self.spec_ladder: tuple = ()
        self.spec_k_now = 0
        self._accept_ewma: Optional[float] = None
        self._spec_ticks_since_adapt = 0
        if self.spec:
            if self.spec_k < 1:
                raise ValueError(
                    f"serving_spec_k must be >= 1: {self.spec_k}")
            if self.spec_adaptive:
                ladder = (spec_k_ladder if spec_k_ladder is not None
                          else _flags.get_flag("serving_spec_k_ladder"))
                self.spec_ladder = self._parse_spec_ladder(ladder)
            else:
                self.spec_ladder = (self.spec_k,)
            # start at the lowest rung: ramping UP on observed
            # acceptance risks nothing, starting high on an unknown
            # workload wastes whole verify chunks
            self.spec_k_now = self.spec_ladder[0]
        if self.spec and not self.spec_model:
            if draft_model is not None:
                raise ValueError(
                    "spec_draft='ngram' is model-free; drop "
                    "draft_model= (or select spec_draft='model')")
        if self.spec_model:
            if draft_model is None:
                raise ValueError(
                    "speculative decoding needs a draft model: "
                    "ServingEngine(model, draft_model=...) — or select "
                    "spec_draft='ngram', or disable "
                    "FLAGS_serving_spec_decode")
            dcfg = draft_model.cfg
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab_size {dcfg.vocab_size} != target "
                    f"{cfg.vocab_size}")
            if dcfg.max_seq_len < self.max_context:
                raise ValueError(
                    f"draft max_seq_len {dcfg.max_seq_len} < engine "
                    f"max_context {self.max_context}")
            self._dsd = draft_model.state_dict()
            self._dkeys = sorted(self._dsd)
            if self.quant_mode:
                self._dqw = _squant.snapshot(
                    self._dkeys,
                    [self._dsd[k]._value for k in self._dkeys],
                    self.quant_mode)
            dnh = dcfg.num_heads
            dhd = dcfg.hidden_size // dnh
            ddtype = self._dsd[self._dkeys[0]]._value.dtype

            def _dpool():
                z = jnp.zeros((dnh, num_blocks + 1, block_size, dhd),
                              ddtype)
                if self._tp_mesh is None:
                    return z
                from jax.sharding import NamedSharding, PartitionSpec
                # draft pools replicate: every rank runs the full
                # (small) draft forward; only the verify is sharded
                return jax.device_put(
                    z, NamedSharding(self._tp_mesh, PartitionSpec()))
            self.dpools = [(_dpool(), _dpool())
                           for _ in range(dcfg.num_layers)]
            if self._tp_mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                rep = NamedSharding(self._tp_mesh, PartitionSpec())
                vals = (self._dqw.values if self._dqw is not None
                        else [self._dsd[k]._value for k in self._dkeys])
                self._tp_draft_vals = jax.tree_util.tree_map(
                    lambda a: jax.device_put(jnp.asarray(a), rep), vals)
        # host-side scheduler state
        self.tables = np.zeros((max_batch, self.nb_per_seq), np.int32)
        self.seq_lens = np.zeros((max_batch,), np.int32)
        self.last_tok = np.zeros((max_batch,), np.int32)
        # per-slot sampling params — device INPUTS of the decode tick
        # (free slots carry the identity: greedy, t=1, no filters)
        self.samp_do = np.zeros((max_batch,), bool)
        self.samp_temp = np.ones((max_batch,), np.float32)
        self.samp_topk = np.zeros((max_batch,), np.int32)
        self.samp_topp = np.ones((max_batch,), np.float32)
        self.samp_seed = np.zeros((max_batch,), np.uint32)
        # tokens DISPATCHED per slot (appended + in-flight): the PRNG
        # stream position and the budget clamp both count these, so an
        # overlapped tick in flight is already accounted for
        self.tok_pos = np.zeros((max_batch,), np.int32)
        self.free_blocks = deque(range(1, num_blocks + 1))
        self.free_slots = deque(range(max_batch))
        self.reserved = 0                      # growth blocks promised
        self.slot_req: List[Optional[Request]] = [None] * max_batch
        self.waiting: deque = deque()
        self.finished: List[Request] = []
        self.steps = 0
        self.ticks = 0
        self.tokens_out = 0
        self.steps_per_tick = max(1, int(steps_per_tick))
        self._decode_fn = None
        self._tick_fns = {}
        self._prefill_fns = {}
        self._prefill_cont_fns = {}
        self._cow_fn = None
        self._last_harvest_t = None
        # --- prefix/KV reuse (ISSUE 9): physical blocks are refcounted
        # (table references + one per index entry) so a prompt prefix
        # resident in the shared-block index is a pointer copy at
        # admission; rc==1 everywhere when the cache is off, making the
        # alloc/release helpers the single accounting path either way
        self.block_rc = np.zeros((num_blocks + 1,), np.int64)
        # blocksan (ISSUE 12): shadow ledger mirroring every
        # _alloc/_ref/_release, reconciled against tables/shadow rows/
        # prefix index at tick boundaries.  None unless
        # FLAGS_enable_jaxsan was on at construction — the disabled
        # path is one `is None` check per accounting call.
        self._blocksan = _jaxsan.block_ledger(num_blocks)
        enable_prefix = (prefix_cache if prefix_cache is not None
                         else _flags.get_flag("serving_prefix_cache"))
        self.prefix = PrefixCache(block_size) if enable_prefix else None
        # the pad-bucket ladder: ONE source of truth for "which prompt
        # shapes exist" — admission padding, worst-case accounting, and
        # the warmup grid all read it (snapshot at construction; the
        # flag is process-wide but a running engine's grid must not
        # shift under an already-taken warmup)
        if pad_buckets is None:
            pad_buckets = _flags.get_flag("serving_pad_buckets")
        ladder = self._parse_pad_buckets(pad_buckets)
        cap = self.nb_per_seq * self.bs
        if ladder:
            ladder = tuple(sorted({min(b, cap) for b in ladder}))
        else:
            ladder = self._default_ladder()
        self.pad_ladder = ladder
        self._warmup_info = None
        # --- chunked prefill (ISSUE 11): absorb arriving prompts in
        # chunks of at most `chunk` tokens, each a suffix-prefill
        # (prefill_cont) program at a traced offset, interleaved between
        # decode ticks by the per-tick scheduler.  Snapshot at
        # construction like the pad ladder: the warmup grid (which
        # programs exist) must not shift under a running engine.
        chunk = (prefill_chunk if prefill_chunk is not None
                 else _flags.get_flag("serving_prefill_chunk"))
        self.chunk = int(chunk)
        if self.chunk < 0:
            raise ValueError(
                f"serving_prefill_chunk must be >= 0: {self.chunk}")
        # admissions mid-chunked-prefill, oldest first (the scheduler
        # finishes the oldest before starting the next: chunk budget
        # spent round-robin would inflate EVERY waiting TTFT)
        self.prefilling: deque = deque()
        # --- paged Pallas kernel selection (ISSUE 18): which chunk-view
        # class the suffix/chunked-prefill and spec-verify programs
        # attend through.  Snapshotted here like the pad ladder — the
        # flags must never be read under trace (graft-lint R004), and a
        # running engine's compiled grid must not shift under it.
        from ..models.kv_cache import (PagedChunkKernelView,
                                       PagedChunkView,
                                       PagedVerifyKernelView)
        self._chunk_view_cls = (
            PagedChunkKernelView
            if _flags.get_flag("serving_pallas_prefill")
            else PagedChunkView)
        self._verify_view_cls = (
            PagedVerifyKernelView
            if _flags.get_flag("serving_pallas_verify")
            else PagedChunkView)
        self.prefill_chunks_total = 0
        self.overlap_chunks_total = 0
        self.slo_sheds = 0
        self._chunks_this_boundary = 0
        self._chunk_s_this_boundary = 0.0
        # readiness (ISSUE 14 satellite): /healthz answers 503 warmup
        # until run()/serve_forever() finished warmup and opened
        # admission — the SSE frontend must not report healthy while
        # the program grid is still compiling
        self._ready = False
        self._t_serve_start: Optional[float] = None
        # --- crash-only lifecycle (ISSUE 15): drain state + tick-error
        # accounting.  `_drain_requested` is a bare bool store, safe
        # from signal handlers and the POST /drain handler threads;
        # the engine loop turns it into an actual drain() at its next
        # boundary.
        self._draining = False
        self._drain_requested = False
        self._drain_info: Optional[dict] = None
        # --- router evidence (ISSUE 16): always-on (independent of the
        # metrics gate) recent admission timestamps + TTFTs.  /healthz
        # ships rate + median so the fleet router's queue-position
        # model can PREDICT a new request's TTFT instead of waiting
        # for an observed SLO breach.  Host-side floats only.
        self._admit_times: deque = deque(maxlen=64)
        self._ttft_recent: deque = deque(maxlen=64)
        self.tick_errors = 0
        self.poisoned_requests = 0
        self.dispatch_retries = 0
        # --- fleet telemetry evidence (ISSUE 17): always-on, host-side
        # floats only — the federation snapshot and the router's SLO
        # burn-rate monitor read these even with the metrics gate off.
        # _ev_tpot is a tick-level sketch (one harvest gap imputed to
        # the k tokens it yielded), NOT per-request timing: the
        # "tracing off = zero per-request work" pin stays intact.
        self._ev_outcomes: Dict[str, int] = {}
        self._ev_tpot = _quantiles.QuantileSketch()
        self._ev_slo_viol = 0
        self._ev_finished = 0
        self._ev_finished_tokens = 0
        # per-engine flight recorder (fleet replicas run several engines
        # in one process; None = the module-global default recorder)
        self._flight_rec = None
        # live chunks_per_tick controller state (ISSUE 17 satellite:
        # FLAGS_serving_chunks_per_tick_auto); None until first consult
        self._chunk_budget_now: Optional[int] = None
        # warm restart: import the newest valid prefix-cache export
        # (hash-chain index + block KV contents) a draining predecessor
        # left under FLAGS_serving_prefix_export_dir — entries re-pin
        # fresh blocks through _alloc_block, corrupt versions are
        # skipped with a counter, and a hot system prompt's first
        # admission is then a suffix-only prefill
        # per-engine override of FLAGS_serving_prefix_export_dir: an
        # in-process replica fleet (inference/fleet/) gives each engine
        # its own export/import root, which a process-global flag
        # cannot express
        self._export_dir = str(
            prefix_export_dir if prefix_export_dir is not None
            else _flags.get_flag("serving_prefix_export_dir"))
        self._prefix_import_info: Optional[dict] = None
        if self.prefix is not None and self._export_dir:
            self._import_prefix_cache(self._export_dir)

    # ------------------------------------------------------------ programs
    def _views(self, pools, tables, seq_lens):
        from ..models.kv_cache import PagedKVCache
        return [PagedKVCache.from_parts(k, v, tables, seq_lens, self.bs)
                for k, v in pools]

    def _bind(self, param_vals):
        for k, v in zip(self._keys, param_vals):
            self._sd[k]._value = v

    def _bind_params(self, param_vals):
        """Bind a program's parameter INPUT into the live model tensors
        (trace time).  A quantized payload dequantizes in-trace first —
        the dequant-in-matmul seam: XLA fuses the per-channel scale
        multiply into the consuming matmuls, and the program's weight
        inputs stay int8 on device."""
        if self._qw is not None:
            param_vals = _squant.dequant_values(param_vals,
                                                self._qw.axes)
        self._bind(param_vals)

    def _bind_draft(self, draft_vals):
        """Same contract for the draft model (spec decode)."""
        if self._dqw is not None:
            draft_vals = _squant.dequant_values(draft_vals,
                                                self._dqw.axes)
        for k, v in zip(self._dkeys, draft_vals):
            self._dsd[k]._value = v

    def _draft_vals(self):
        """The draft-parameter program input: the TP-replicated or
        quantized snapshot when one exists, else the live tensors (the
        degree-1 fp contract: weight updates reach the next dispatch)."""
        if self._tp_draft_vals is not None:
            return self._tp_draft_vals
        if self._dqw is not None:
            return self._dqw.values
        return [self._dsd[k]._value for k in self._dkeys]

    @contextmanager
    def _params_for_call(self):
        """The program-parameter argument plus the save/restore bracket
        the degree-1 path needs (its programs re-bind the model's live
        tensors while tracing).  TP target programs are pure functions
        of the sharded snapshot — but the draft model is bound at trace
        time in EVERY mode, so its tensors always get the bracket."""
        dsaved = ({k: self._dsd[k]._value for k in self._dkeys}
                  if self._dsd is not None else None)
        try:
            if self._tp_params is not None:
                yield self._tp_params
                return
            vals = (self._qw.values if self._qw is not None
                    else [self._sd[k]._value for k in self._keys])
            saved = {k: self._sd[k]._value for k in self._keys}
            try:
                yield vals
            finally:
                for k, v in saved.items():
                    self._sd[k]._value = v
        finally:
            if dsaved is not None:
                for k, v in dsaved.items():
                    self._dsd[k]._value = v

    def _blame(self, *extra):
        base = (("max_batch", self.B), ("block_size", self.bs))
        if self.tp > 1:
            base = base + (("tp", self.tp),)
        return extra + base

    def _shard_tp(self, fn, in_specs, out_specs):
        """Wrap a program body in shard_map over the tp mesh.  By
        convention the params arg takes the plan's spec tree, the pools
        arg P('tp') (head axis), and every scheduler input P() — the
        rank-0 broadcast.  check_vma off: replication of the outputs is
        guaranteed by construction (every rank computes the full logits
        after the vocab all-gather), which the rep-checker cannot always
        prove through the sampling primitives."""
        from ..core import jax_compat as _jc
        return _jc.shard_map(fn, mesh=self._tp_mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)

    def _decode_program(self):
        if self._decode_fn is not None:
            return self._decode_fn
        if self._tp_mesh is not None:
            self._decode_fn = self._build_tp_decode()
            return self._decode_fn
        from ..framework.dygraph import no_grad

        def step(param_vals, pools, tables, seq_lens, last_tok):
            self._bind_params(param_vals)
            views = self._views(pools, tables, seq_lens)
            with no_grad():
                logits_t, new_views = self.model.forward_with_cache(
                    Tensor._wrap(last_tok[:, None]), views,
                    pos_offset=Tensor._wrap(seq_lens[:, None]))
            logits = logits_t._value[:, -1, :]
            new_pools = [(c.k, c.v) for c in new_views]
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), \
                logits, new_pools

        donate = (1,) if jax.default_backend() != "cpu" else ()
        self._decode_fn = _compile.wrap_first_call(
            jax.jit(step, donate_argnums=donate), "serving.decode",
            self._blame(("variant", "host_sampling_k1")))
        return self._decode_fn

    def _tick_program(self, k: int):
        """The fast-path k-step tick with ON-DEVICE sampling.

        Per-slot `do_sample`/`temperature`/`top_k`/`top_p`/`seed` ride
        in as arrays, so one compiled program serves every batch mix
        (the reference samples inside its decode megakernel for the
        same reason).  Each step's token for a sampling row is drawn
        from ``fold_in(key(seed), token_position)`` — the stream is a
        pure function of (seed, position), independent of tick
        boundaries, overlap, or slot placement."""
        fn = self._tick_fns.get(k)
        if fn is not None:
            return fn
        if self._tp_mesh is not None:
            fn = self._tick_fns[k] = self._build_tp_tick(k)
            return fn
        from ..framework.dygraph import no_grad

        def tick(param_vals, pools, tables, seq_lens, last_tok,
                 do_sample, temperature, top_k, top_p, seeds, tok_pos):
            self._bind_params(param_vals)

            def body(carry, j):
                pools, lens, last = carry
                views = self._views(pools, tables, lens)
                with no_grad():
                    logits_t, new_views = self.model.forward_with_cache(
                        Tensor._wrap(last[:, None]), views,
                        pos_offset=Tensor._wrap(lens[:, None]))
                logits = logits_t._value[:, -1, :]
                nxt = _next_tokens(logits, do_sample, temperature,
                                   top_k, top_p, seeds, tok_pos, j)
                active = lens > 0
                nxt = jnp.where(active, nxt, 0)
                lens = jnp.where(active, lens + 1, 0)
                new_pools = [(c.k, c.v) for c in new_views]
                return (new_pools, lens, nxt), nxt

            (pools, _, _), toks = jax.lax.scan(
                body, (pools, seq_lens, last_tok), jnp.arange(k))
            return jnp.transpose(toks), pools        # [B, k]

        donate = (1,) if jax.default_backend() != "cpu" else ()
        fn = self._tick_fns[k] = _compile.wrap_first_call(
            jax.jit(tick, donate_argnums=donate), "serving.tick",
            self._blame(("steps_per_tick", k)))
        return fn

    # ------------------------------------------------------ TP programs
    def _build_tp_tick(self, k: int):
        """The k-step tick as a shard_map program: same scan/sampling
        shape as the degree-1 tick, with the forward running on each
        rank's weight/pool shards (`tp.forward_tp`).  Token choice sees
        the FULL logits (replicated after the vocab all-gather), so the
        streams are bit-identical to degree 1."""
        from jax.sharding import PartitionSpec as _P
        from . import tp as _tp
        meta, bs = self._tp_meta, self.bs

        def tick(params, pools, tables, seq_lens, last_tok,
                 do_sample, temperature, top_k, top_p, seeds, tok_pos):
            def body(carry, j):
                pools, lens, last = carry
                logits, pools = _tp.forward_tp(
                    meta, params, last[:, None], pools, tables, lens,
                    lens[:, None], bs)
                nxt = _next_tokens(logits[:, -1, :], do_sample,
                                   temperature, top_k, top_p, seeds,
                                   tok_pos, j)
                active = lens > 0
                nxt = jnp.where(active, nxt, 0)
                lens = jnp.where(active, lens + 1, 0)
                return (pools, lens, nxt), nxt

            (pools, _, _), toks = jax.lax.scan(
                body, (pools, seq_lens, last_tok), jnp.arange(k))
            return jnp.transpose(toks), pools

        body = self._shard_tp(
            tick, (self._tp_specs, _tp.pool_spec()) + (_P(),) * 9,
            (_P(), _tp.pool_spec()))
        donate = (1,) if jax.default_backend() != "cpu" else ()
        return _compile.wrap_first_call(
            jax.jit(body, donate_argnums=donate), "serving.tick",
            self._blame(("steps_per_tick", k)))

    def _build_tp_decode(self):
        from jax.sharding import PartitionSpec as _P
        from . import tp as _tp
        meta, bs = self._tp_meta, self.bs

        def step(params, pools, tables, seq_lens, last_tok):
            logits, pools = _tp.forward_tp(
                meta, params, last_tok[:, None], pools, tables, seq_lens,
                seq_lens[:, None], bs)
            logits = logits[:, -1, :]
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), \
                logits, pools

        body = self._shard_tp(
            step, (self._tp_specs, _tp.pool_spec()) + (_P(),) * 3,
            (_P(), _P(), _tp.pool_spec()))
        donate = (1,) if jax.default_backend() != "cpu" else ()
        return _compile.wrap_first_call(
            jax.jit(body, donate_argnums=donate), "serving.decode",
            self._blame(("variant", "host_sampling_k1")))

    def _prefill_program(self, L_pad: int):
        fn = self._prefill_fns.get(L_pad)
        if fn is not None:
            return fn
        if self._tp_mesh is not None:
            fn = self._prefill_fns[L_pad] = self._build_tp_prefill(L_pad)
            return fn
        from ..framework.dygraph import no_grad

        def prefill(param_vals, pools, table_row, prompt, true_len):
            self._bind_params(param_vals)
            zero = jnp.zeros((1,), jnp.int32)
            views = self._views(pools, table_row, zero)
            with no_grad():
                logits_t, new_views = self.model.forward_with_cache(
                    Tensor._wrap(prompt), views, pos_offset=0)
            # last REAL token's logits (prompt is right-padded to L_pad)
            row = jax.lax.dynamic_index_in_dim(
                logits_t._value[0], true_len - 1, axis=0, keepdims=False)
            new_pools = [(c.k, c.v) for c in new_views]
            return row, new_pools

        if self.spec_model:
            def prefill_spec(param_vals, draft_vals, pools, dpools,
                             table_row, prompt, true_len):
                row, new_pools = prefill(param_vals, pools, table_row,
                                         prompt, true_len)
                self._bind_draft(draft_vals)
                dnew = self._draft_prompt_write(dpools, table_row, prompt)
                return row, new_pools, dnew
            body, donate = prefill_spec, (2, 3)
        else:
            body, donate = prefill, (1,)
        donate = donate if jax.default_backend() != "cpu" else ()
        fn = self._prefill_fns[L_pad] = _compile.wrap_first_call(
            jax.jit(body, donate_argnums=donate), "serving.prefill",
            self._blame(("L_pad", L_pad)))
        return fn

    def _draft_prompt_write(self, dpools, table_row, prompt, start=None):
        """Traced helper: run the draft forward over a (padded) prompt
        chunk purely for its KV WRITES — the logits are discarded (the
        request's first token comes from the target prefill).  With
        ``start`` the chunk is a suffix at that offset (prefix-cache
        hit; the shared blocks already hold the prefix's draft KV from
        the admission that registered them)."""
        from ..framework.dygraph import no_grad
        from ..models.kv_cache import PagedKVCache
        if start is None:
            lens, cls, off = jnp.zeros((1,), jnp.int32), PagedKVCache, 0
        else:
            lens, cls, off = jnp.reshape(start, (1,)), \
                self._chunk_view_cls, Tensor._wrap(start)
        dviews = [cls.from_parts(kk, vv, table_row, lens, self.bs)
                  for kk, vv in dpools]
        with no_grad():
            _, dnew = self.draft.forward_with_cache(
                Tensor._wrap(prompt), dviews, pos_offset=off)
        return [(c.k, c.v) for c in dnew]

    def _build_tp_prefill(self, L_pad: int):
        from jax.sharding import PartitionSpec as _P
        from . import tp as _tp
        meta, bs = self._tp_meta, self.bs

        def prefill(params, pools, table_row, prompt, true_len):
            zero = jnp.zeros((1,), jnp.int32)
            logits, pools = _tp.forward_tp(
                meta, params, prompt, pools, table_row, zero, 0, bs)
            row = jax.lax.dynamic_index_in_dim(
                logits[0], true_len - 1, axis=0, keepdims=False)
            return row, pools

        if self.spec_model:
            def prefill_spec(params, draft_vals, pools, dpools,
                             table_row, prompt, true_len):
                row, pools = prefill(params, pools, table_row, prompt,
                                     true_len)
                self._bind_draft(draft_vals)
                dnew = self._draft_prompt_write(dpools, table_row, prompt)
                return row, pools, dnew
            body = self._shard_tp(
                prefill_spec,
                (self._tp_specs, _P(), _tp.pool_spec(), _P(), _P(),
                 _P(), _P()),
                (_P(), _tp.pool_spec(), _P()))
            donate = (2, 3)
        else:
            body = self._shard_tp(
                prefill,
                (self._tp_specs, _tp.pool_spec(), _P(), _P(), _P()),
                (_P(), _tp.pool_spec()))
            donate = (1,)
        donate = donate if jax.default_backend() != "cpu" else ()
        return _compile.wrap_first_call(
            jax.jit(body, donate_argnums=donate), "serving.prefill",
            self._blame(("L_pad", L_pad)))

    def _prefill_cont_program(self, L_pad: int):
        """Suffix prefill for a prefix-cache hit: the first ``start``
        tokens' KV is already resident through the slot's table (shared
        blocks); this program writes ONLY the suffix chunk (padded to
        the same ladder bucket the full prefill uses — the warmup grid
        stays enumerable) at positions start..start+true_len-1 and
        returns the last real token's logits.  ``start`` is a traced
        scalar, so one program per bucket serves every split point."""
        fn = self._prefill_cont_fns.get(L_pad)
        if fn is not None:
            return fn
        chunk_view_cls = self._chunk_view_cls

        if self._tp_mesh is not None:
            from jax.sharding import PartitionSpec as _P
            from . import tp as _tp
            meta, bs = self._tp_meta, self.bs

            def cont(params, pools, table_row, suffix, true_len, start):
                lens = jnp.reshape(start, (1,))
                logits, pools = _tp.forward_tp(
                    meta, params, suffix, pools, table_row, lens, start,
                    bs, view_cls=chunk_view_cls)
                row = jax.lax.dynamic_index_in_dim(
                    logits[0], true_len - 1, axis=0, keepdims=False)
                return row, pools

            if self.spec_model:
                def cont_spec(params, draft_vals, pools, dpools,
                              table_row, suffix, true_len, start):
                    row, pools = cont(params, pools, table_row, suffix,
                                      true_len, start)
                    self._bind_draft(draft_vals)
                    dnew = self._draft_prompt_write(dpools, table_row,
                                                    suffix, start=start)
                    return row, pools, dnew
                body = self._shard_tp(
                    cont_spec,
                    (self._tp_specs, _P(), _tp.pool_spec()) + (_P(),) * 5,
                    (_P(), _tp.pool_spec(), _P()))
                donate = (2, 3)
            else:
                body = self._shard_tp(
                    cont, (self._tp_specs, _tp.pool_spec()) + (_P(),) * 4,
                    (_P(), _tp.pool_spec()))
                donate = (1,)
            donate = donate if jax.default_backend() != "cpu" else ()
            fn = self._prefill_cont_fns[L_pad] = _compile.wrap_first_call(
                jax.jit(body, donate_argnums=donate),
                "serving.prefill_cont", self._blame(("L_pad", L_pad)))
            return fn
        from ..framework.dygraph import no_grad

        def cont(param_vals, pools, table_row, suffix, true_len, start):
            self._bind_params(param_vals)
            lens = jnp.reshape(start, (1,))
            views = [chunk_view_cls.from_parts(kk, vv, table_row, lens,
                                               self.bs)
                     for kk, vv in pools]
            with no_grad():
                logits_t, new_views = self.model.forward_with_cache(
                    Tensor._wrap(suffix), views,
                    pos_offset=Tensor._wrap(start))
            row = jax.lax.dynamic_index_in_dim(
                logits_t._value[0], true_len - 1, axis=0, keepdims=False)
            new_pools = [(c.k, c.v) for c in new_views]
            return row, new_pools

        if self.spec_model:
            def cont_spec(param_vals, draft_vals, pools, dpools,
                          table_row, suffix, true_len, start):
                row, new_pools = cont(param_vals, pools, table_row,
                                      suffix, true_len, start)
                self._bind_draft(draft_vals)
                dnew = self._draft_prompt_write(dpools, table_row,
                                                suffix, start=start)
                return row, new_pools, dnew
            body, donate = cont_spec, (2, 3)
        else:
            body, donate = cont, (1,)
        donate = donate if jax.default_backend() != "cpu" else ()
        fn = self._prefill_cont_fns[L_pad] = _compile.wrap_first_call(
            jax.jit(body, donate_argnums=donate), "serving.prefill_cont",
            self._blame(("L_pad", L_pad)))
        return fn

    def _cow_program(self):
        """Copy-on-write block copy: duplicate physical block ``src``
        into ``dst`` across every layer's pools, on device (one program;
        src/dst are traced scalars).  Admission uses it when a shared
        block must receive the recomputed last prompt token.  With spec
        decode the draft pools share the block ids, so the same program
        copies the draft layers too."""
        if self._cow_fn is not None:
            return self._cow_fn

        def cow(pools, src, dst):
            out = []
            for kk, vv in pools:
                out.append((kk.at[:, dst].set(kk[:, src]),
                            vv.at[:, dst].set(vv[:, src])))
            return out

        if self.spec_model:
            def body(pools, dpools, src, dst):
                return cow(pools, src, dst), cow(dpools, src, dst)
            donate = (0, 1)
        else:
            body, donate = cow, (0,)
        if self._tp_mesh is not None:
            from jax.sharding import PartitionSpec as _P
            from . import tp as _tp
            if self.spec_model:
                body = self._shard_tp(
                    body, (_tp.pool_spec(), _P(), _P(), _P()),
                    (_tp.pool_spec(), _P()))
            else:
                body = self._shard_tp(body, (_tp.pool_spec(), _P(), _P()),
                                      _tp.pool_spec())
        donate = donate if jax.default_backend() != "cpu" else ()
        self._cow_fn = _compile.wrap_first_call(
            jax.jit(body, donate_argnums=donate), "serving.cow",
            self._blame())
        return self._cow_fn

    def _spec_program(self, k: int):
        """The compiled MODEL-draft speculative tick for ladder rung
        ``k`` (draft k-step scan + target k-token chunk verify + accept
        masks — `inference/speculative.py`).  Signature: (params,
        draft_params, pools, dpools, tables, seq_lens, last_tok,
        do_sample, temperature, top_k, top_p, seeds, kcap) -> (toks
        [B,k], counts, accepts, new_lens, new_last, pools, dpools).
        Cached PER K — the adaptive ladder steps between compiled
        programs, never recompiles one (every rung is in the warmup
        grid).  Under TP the draft runs replicated while the verify is
        the sharded forward; every scheduler input stays the rank-0
        broadcast."""
        fn = self._spec_fns.get(k)
        if fn is not None:
            return fn
        from . import speculative as _spec
        if self._tp_mesh is not None:
            from jax.sharding import PartitionSpec as _P
            from . import tp as _tp
            body = self._shard_tp(
                _spec.build_tp_spec_tick(self, k),
                (self._tp_specs, _P(), _tp.pool_spec(), _P())
                + (_P(),) * 9,
                (_P(),) * 5 + (_tp.pool_spec(), _P()))
        else:
            body = _spec.build_spec_tick(self, k)
        donate = (2, 3) if jax.default_backend() != "cpu" else ()
        fn = self._spec_fns[k] = _compile.wrap_first_call(
            jax.jit(body, donate_argnums=donate), "serving.spec_tick",
            self._blame(("spec_k", k), ("draft", "model")))
        return fn

    def _spec_hd_program(self, k: int):
        """The compiled HOST-draft (ngram) speculative tick for ladder
        rung ``k``: the k proposed tokens are a device input, so the
        program is the verify chunk + accept tail alone — no draft
        params or pools in the signature.  (params, pools, tables,
        seq_lens, last_tok, dtoks, do_sample, temperature, top_k,
        top_p, seeds, kcap) -> (toks, counts, accepts, new_lens,
        new_last, pools).  Cached per k like the model twin."""
        fn = self._spec_hd_fns.get(k)
        if fn is not None:
            return fn
        from . import speculative as _spec
        if self._tp_mesh is not None:
            from jax.sharding import PartitionSpec as _P
            from . import tp as _tp
            body = self._shard_tp(
                _spec.build_tp_hostdraft_tick(self, k),
                (self._tp_specs, _tp.pool_spec()) + (_P(),) * 10,
                (_P(),) * 5 + (_tp.pool_spec(),))
        else:
            body = _spec.build_hostdraft_tick(self, k)
        donate = (1,) if jax.default_backend() != "cpu" else ()
        fn = self._spec_hd_fns[k] = _compile.wrap_first_call(
            jax.jit(body, donate_argnums=donate), "serving.spec_tick",
            self._blame(("spec_k", k), ("draft", "ngram")))
        return fn

    # -------------------------------------------------------------- warmup
    def _warm_call(self, fn, args, aot, install):
        """Consume one program's compile during warmup.

        AOT path: ``.lower().compile()`` the inner jit function, run the
        executable once on the inert dummy args (validates the call
        convention and threads the donated pools through), and install a
        shim that calls the compiled executable directly — later traffic
        never re-enters jit tracing at all.  Anything raising falls back
        to a plain dummy-input call of the wrapped program, which marks
        its `wrap_first_call` tracker entry compiled the ordinary way.
        Returns (program output, used_aot)."""
        inner = getattr(fn, "__wrapped__", None)
        mark = getattr(fn, "_mark_compiled", None)
        entry = getattr(fn, "_xray_entry", None)
        if aot and inner is not None and mark is not None \
                and hasattr(inner, "lower"):
            try:
                t0 = time.perf_counter()
                # the claims capture collects trace-time claim_kernel
                # calls from the Pallas wrappers: interpret-mode kernels
                # leave no custom-call marker in the lowered text, so
                # this is the only evidence channel the coverage audit
                # has for them
                with _xray.capture_kernel_claims() as claims:
                    lowered = inner.lower(*args)
                compiled = lowered.compile()
                # the validation run counts as a dispatch too, so every
                # warmed program is named in the ledger before traffic
                out = _xray.dispatch(entry, compiled, args, {}) \
                    if entry is not None else compiled(*args)
                mark(time.perf_counter() - t0)
                # static cost + kernel audit: cost_analysis() FLOPs/
                # bytes, the custom-call scan of the lowered text, and
                # the trace-time kernel claims (best-effort; never
                # raises)
                _xray.attach_lowered(entry, lowered, claims)

                def shim(*a, _c=compiled, _e=entry):
                    if _e is not None:
                        return _xray.dispatch(_e, _c, a, {})
                    return _c(*a)
                shim.__wrapped__ = inner
                shim._xray_entry = entry
                install(shim)
                return out, True
            except Exception:  # noqa: BLE001 - AOT is an optimization;
                pass           # the jit path below always works
        return fn(*args), False

    def warmup(self, aot: bool = True) -> dict:
        """Precompile the COMPLETE program grid this engine can ever
        dispatch, before traffic arrives: one tick program per tick size
        in {steps_per_tick, 1} (greedy and sampled decode share each —
        per-slot sampling params are device inputs and both `lax.cond`
        branches compile), the host-sampling k=1 decode program, and one
        prefill program per pad-ladder bucket.  BOTH sampling variants
        warm regardless of the current ``FLAGS_serving_device_sampling``
        value: the flag is read live at every dispatch, so a mid-run
        flip must not route traffic to an un-warmed program.  Dummy
        inputs are inert: all-zero tables and seq_lens route every
        write to the reserved scratch block 0 and hold every slot
        inactive, so warmup is safe even mid-flight.

        Idempotent; returns (and stashes for ``stats()``) ``{warmup_s,
        programs, aot_programs, grid}``.  After warmup, traffic over the
        ladder triggers ZERO compile-tracker events — the acceptance
        criterion ``FLAGS_serving_warmup=1`` buys."""
        if self._warmup_info is not None:
            return self._warmup_info
        t0 = time.perf_counter()
        B, nb = self.B, self.nb_per_seq
        z = lambda shape, dt: jnp.zeros(shape, dt)  # noqa: E731
        grid = []
        n_aot = 0
        with self._params_for_call() as param_vals:
            samp = (z((B,), jnp.bool_), jnp.ones((B,), jnp.float32),
                    z((B,), jnp.int32), jnp.ones((B,), jnp.float32),
                    z((B,), jnp.uint32), z((B,), jnp.int32))
            sched = (z((B, nb), jnp.int32), z((B,), jnp.int32),
                     z((B,), jnp.int32))
            # spec-decode engines thread (draft_params, draft_pools)
            # through prefill/cont/cow and own the spec tick program
            dvals = self._draft_vals() if self.spec_model else None

            def _set_dpools(out_tail):
                if self.spec_model:
                    self.dpools = out_tail
            for k in sorted({self.steps_per_tick, 1}, reverse=True):
                out, was_aot = self._warm_call(
                    self._tick_program(k),
                    (param_vals, self.pools) + sched + samp, aot,
                    lambda f, _k=k: self._tick_fns.__setitem__(_k, f))
                self.pools = out[1]
                n_aot += was_aot
                grid.append({"program": "tick", "steps_per_tick": k})
            out, was_aot = self._warm_call(
                self._decode_program(),
                (param_vals, self.pools) + sched, aot,
                lambda f: setattr(self, "_decode_fn", f))
            self.pools = out[2]
            n_aot += was_aot
            grid.append({"program": "decode", "steps_per_tick": 1})
            if self.spec:
                # one spec program per LADDER rung (adaptive k steps
                # between warmed programs, never into a compile); the
                # host-draft variant threads no draft state at all
                for sk in self.spec_ladder:
                    if self.spec_model:
                        out, was_aot = self._warm_call(
                            self._spec_program(sk),
                            (param_vals, dvals, self.pools, self.dpools)
                            + sched + samp[:5]
                            + (z((B,), jnp.int32),), aot,
                            lambda f, _k=sk:
                                self._spec_fns.__setitem__(_k, f))
                        self.pools, self.dpools = out[5], out[6]
                    else:
                        out, was_aot = self._warm_call(
                            self._spec_hd_program(sk),
                            (param_vals, self.pools) + sched
                            + (z((B, sk), jnp.int32),) + samp[:5]
                            + (z((B,), jnp.int32),), aot,
                            lambda f, _k=sk:
                                self._spec_hd_fns.__setitem__(_k, f))
                        self.pools = out[5]
                    n_aot += was_aot
                    grid.append({"program": "spec_tick", "spec_k": sk,
                                 "draft": self.spec_kind})
            if self.chunk <= 0:
                # monolithic prefill: one program per ladder bucket.  A
                # CHUNKED engine (FLAGS_serving_prefill_chunk > 0) never
                # dispatches these — every admission runs the
                # suffix-prefill chunk programs below instead, so the
                # grid swaps one program family for the other.
                for L_pad in self.pad_ladder:
                    dpref = ((dvals, self.pools, self.dpools)
                             if self.spec_model else (self.pools,))
                    out, was_aot = self._warm_call(
                        self._prefill_program(L_pad),
                        (param_vals,) + dpref + (z((1, nb), jnp.int32),
                         z((1, L_pad), jnp.int32), jnp.int32(1)), aot,
                        lambda f, _L=L_pad:
                            self._prefill_fns.__setitem__(_L, f))
                    self.pools = out[1]
                    _set_dpools(out[2] if self.spec_model else None)
                    n_aot += was_aot
                    grid.append({"program": "prefill", "L_pad": L_pad})
            if self.prefix is not None or self.chunk > 0:
                # suffix-prefill-at-offset programs: the prefix-cache
                # hit path AND the chunked-prefill path (one program per
                # ladder bucket; `start` is traced, so every split point
                # and chunk offset shares it).  Dummies are inert: an
                # all-zero table routes every write to scratch block 0.
                for L_pad in self.pad_ladder:
                    dpref = ((dvals, self.pools, self.dpools)
                             if self.spec_model else (self.pools,))
                    out, was_aot = self._warm_call(
                        self._prefill_cont_program(L_pad),
                        (param_vals,) + dpref + (z((1, nb), jnp.int32),
                         z((1, L_pad), jnp.int32), jnp.int32(1),
                         jnp.int32(0)), aot,
                        lambda f, _L=L_pad:
                            self._prefill_cont_fns.__setitem__(_L, f))
                    self.pools = out[1]
                    _set_dpools(out[2] if self.spec_model else None)
                    n_aot += was_aot
                    grid.append({"program": "prefill_cont",
                                 "L_pad": L_pad})
            if self.prefix is not None:
                # the CoW block copy (the cache copies block 0 onto
                # itself during warmup — inert)
                cow_args = ((self.pools, self.dpools) if self.spec_model
                            else (self.pools,))
                out, was_aot = self._warm_call(
                    self._cow_program(),
                    cow_args + (jnp.int32(0), jnp.int32(0)), aot,
                    lambda f: setattr(self, "_cow_fn", f))
                if self.spec_model:
                    self.pools, self.dpools = out
                else:
                    self.pools = out
                n_aot += was_aot
                grid.append({"program": "cow"})
        self._warmup_info = {
            "warmup_s": round(time.perf_counter() - t0, 4),
            "programs": len(grid), "aot_programs": n_aot, "grid": grid}
        return self._warmup_info

    # ----------------------------------------------------------- scheduler
    @staticmethod
    def _parse_pad_buckets(spec) -> tuple:
        """FLAGS_serving_pad_buckets / the `pad_buckets` kwarg: a
        comma-separated string or int sequence; () = use the default
        power-of-two ladder."""
        if spec is None:
            return ()
        if isinstance(spec, str):
            vals = [int(s) for s in
                    (c.strip() for c in spec.split(",")) if s]
        else:
            vals = [int(v) for v in spec]
        if any(v <= 0 for v in vals):
            raise ValueError(
                f"serving_pad_buckets entries must be positive: {vals}")
        return tuple(vals)

    @staticmethod
    def _parse_spec_ladder(spec) -> tuple:
        """FLAGS_serving_spec_k_ladder / the ``spec_k_ladder`` kwarg:
        comma-separated string or int sequence; sorted, deduplicated,
        every rung >= 2 (a 1-rung emits exactly one token per verify —
        that is the PLAIN path's job)."""
        if isinstance(spec, str):
            vals = [int(s) for s in
                    (c.strip() for c in spec.split(",")) if s]
        else:
            vals = [int(v) for v in spec]
        if not vals or any(v < 2 for v in vals):
            raise ValueError(
                "serving_spec_k_ladder needs at least one rung, all "
                f">= 2: {vals}")
        return tuple(sorted(set(vals)))

    def _default_ladder(self) -> tuple:
        """Power-of-two buckets from block_size up, clamped to the block
        table — exactly the shapes the legacy `_pad_bucket` formula
        (min(pow2, capacity)) could produce, materialized so the warmup
        grid can enumerate them."""
        cap = self.nb_per_seq * self.bs
        out, b = [], max(self.bs, 1)
        while b < cap:
            out.append(b)
            b *= 2
        out.append(cap)
        return tuple(out)

    def _pad_bucket(self, L: int) -> int:
        """Prompt pad length: smallest ladder bucket that fits (bounds
        the number of compiled prefill programs), CLAMPED to the
        block-table capacity.  Without the clamp a non-power-of-two
        max_context (e.g. 96 with block_size 16, prompt 70 -> bucket
        128) makes need_now exceed nb_per_seq and admission crashes
        mid-flight leaking blocks (ADVICE r5 #1/#4).  A prompt beyond a
        CUSTOM ladder's top rung falls back to the power-of-two bucket
        (still clamped): the request is served, at the price of one
        compile the tracker blames on the new L_pad."""
        for b in self.pad_ladder:
            if L <= b:
                return b
        return min(_bucket(L, self.bs), self.nb_per_seq * self.bs)

    def add_request(self, req: Request):
        L = len(req.prompt_ids)
        traced = _metrics.enabled()
        if self._draining or self._drain_requested:
            # admission is CLOSED while draining: new traffic belongs
            # on another replica (healthz already answers 503 draining)
            _M_REJECTIONS.inc(reason="draining")
            self._ev_note("rejected:draining")
            if traced:
                self._reject_trace(req, "draining")
            raise ValueError(
                "engine is draining: admission closed (retry against "
                "another replica)")
        if L + req.max_new_tokens > self.max_context:
            _M_REJECTIONS.inc(reason="over_context")
            self._ev_note("rejected:over_context")
            if traced:
                self._reject_trace(req, "over_context")
            raise ValueError(
                f"request needs {L + req.max_new_tokens}"
                f" tokens > max_context {self.max_context}")
        # worst-case block need must fit the POOL outright, or admission
        # can never succeed and run() would spin on the waiting queue.
        # Uses the SAME clamped pad formula as _try_admit, so a request
        # accepted here can never out-size the block table at admission.
        worst = self._blocks_for(self._pad_bucket(L)) + max(
            0, self._blocks_for(L + req.max_new_tokens)
            - self._blocks_for(L))
        if worst > self.num_blocks:
            _M_REJECTIONS.inc(reason="capacity")
            self._ev_note("rejected:capacity")
            if traced:
                self._reject_trace(req, "capacity")
            raise ValueError(
                f"request needs {worst} blocks worst-case but the pool "
                f"has {self.num_blocks}; raise num_blocks or lower "
                "max_new_tokens")
        # two enqueue stamps, deliberately separate: `_t_enqueue` stays
        # metrics-gated (tracing off really does zero TRACING work —
        # pinned), while `_t_enqueue_ev` is the always-on router
        # evidence the /healthz TTFT predictor reads even on engines
        # running with metrics disabled
        if traced:
            req._t_enqueue = time.perf_counter()
        req._t_enqueue_ev = time.perf_counter()
        self.waiting.append(req)
        self._update_pressure()
        return req

    def _reject_trace(self, req: Request, reason: str) -> None:
        """Rejections are lifecycle endpoints too: a scraper reading
        /requests sees WHY traffic bounced, not just that it did."""
        req.outcome = reason
        rec = {"rid": req.rid, "outcome": f"rejected:{reason}",
               "prompt_len": len(req.prompt_ids),
               "max_new_tokens": req.max_new_tokens,
               **req._trace_ctx()}
        req.trace = rec
        self._flightrec().record_event("request", **rec)
        _export.record_request(rec)

    def _flightrec(self) -> "_flight.FlightRecorder":
        """This engine's flight recorder: the injected per-engine one
        (fleet replicas — several engines in one process must not
        interleave their rings) or the module-global default."""
        rec = self._flight_rec
        return rec if rec is not None else _flight.default_recorder()

    def _ev_note(self, outcome: str) -> None:
        """Always-on terminal-outcome tally (fleet federation + SLO
        burn-rate evidence); the metrics twin feeds the scrape."""
        self._ev_outcomes[outcome] = self._ev_outcomes.get(outcome, 0) + 1
        _M_OUTCOMES.inc(outcome=outcome)

    def _blocks_for(self, n_tokens: int) -> int:
        return math.ceil(n_tokens / self.bs)

    # --------------------------------------------- block refcounting
    # Physical blocks are refcounted so the prefix index and multiple
    # request tables can share them.  With the cache off every block has
    # exactly one reference (its table) and these reduce to the old
    # popleft/append accounting.
    def _alloc_block(self) -> int:
        blk = self.free_blocks.popleft()
        if self._blocksan is not None:
            self._blocksan.alloc(blk)
        self.block_rc[blk] = 1
        return blk

    def _ref_block(self, blk: int) -> None:
        if self._blocksan is not None:
            self._blocksan.ref(blk)
        self.block_rc[blk] += 1

    def _release_block(self, blk: int) -> bool:
        """Drop one reference; frees the block (returns True) only when
        orphaned — a shared block survives its other holders."""
        if self._blocksan is not None:
            self._blocksan.release(blk)
        self.block_rc[blk] -= 1
        if self.block_rc[blk] <= 0:
            self.block_rc[blk] = 0
            self.free_blocks.append(blk)
            return True
        return False

    # ------------------------------------- failure isolation (ISSUE 15)
    _POISON_STRIKES = 2
    _DISPATCH_BACKOFF_S = 0.05

    def _dispatch_call(self, site: str, call):
        """Run one compiled-program dispatch through the chaos site hook
        and the bounded transient-retry policy
        (``FLAGS_serving_dispatch_retries``): a RuntimeError (the
        XlaRuntimeError family) retries in place with the shared
        io_retry exponential backoff before surfacing to the tick
        guard.  With the flag at 0 (default) and no chaos armed this is
        one dict check + the call."""
        def attempt():
            _chaos.inject(site)
            return call()

        retries = int(_flags.get_flag("serving_dispatch_retries"))
        if retries <= 0:
            return attempt()
        from ..distributed.checkpoint.io_retry import call_with_retries
        return call_with_retries(
            attempt, retries=retries, backoff_s=self._DISPATCH_BACKOFF_S,
            site=site, retry_on=(RuntimeError, OSError),
            counter=_RetryCounter(self))

    def _screen_row(self, row, slot: int, req: Request) -> np.ndarray:
        """Host-materialize a prefill logits row and screen it.

        Chaos may corrupt the armed (slot, rid)'s row in place (the
        NaN-forward injection); with the flight-recorder NaN watchdog
        enabled the row is then probed and a non-finite value raises
        :class:`NonFiniteLogits` — BEFORE prefix registration, so a NaN
        prompt can never poison the shared index, and before any token
        is emitted, so the strike/requeue path replays nothing.  With
        the watchdog off (default) the row is materialized exactly as
        `_finish_admission` always did and never reduced."""
        row_np = np.asarray(row)
        if _chaos.nan_payload("serving.prefill", slot=slot, rid=req.rid):
            row_np = np.full_like(row_np, np.nan)
        if _flight.enabled() and not _flight.check_finite(
                float(np.sum(row_np)), site="serving.prefill.logits"):
            raise NonFiniteLogits(
                f"prefill logits non-finite for rid={req.rid}")
        return row_np

    def _screen_decode_logits(self, pend):
        """Host-materialize the host-sampling decode tick's logits and
        screen the active rows (chaos NaN injection + watchdog probe).
        Returns ``(logits ndarray or None, {slot: error})``.  Gated the
        same way as `_screen_row`: with the watchdog off and no chaos
        armed, nothing is materialized beyond what the sampler itself
        would have pulled."""
        if not _flight.enabled() and not _chaos.active_faults():
            return None, {}
        logits_np = np.array(np.asarray(pend.logits))
        bad: dict = {}
        for slot in pend.active:
            req = pend.reqs[slot]
            if req is None or req.done:
                continue
            if _chaos.nan_payload("serving.decode", slot=slot,
                                  rid=req.rid):
                logits_np[slot] = np.nan
            if _flight.enabled() and not _flight.check_finite(
                    float(np.sum(logits_np[slot])),
                    site="serving.decode.logits"):
                bad[slot] = "non-finite decode logits"
        return logits_np, bad

    def _materialize(self, handle):
        """Block on a tick's device outputs, under the tick watchdog:
        with ``FLAGS_serving_tick_timeout_s`` > 0 the wait runs on a
        helper thread and a harvest that does not materialize in time
        raises :class:`TickTimeout` (the guard then fails the tick)
        instead of wedging the loop on a hung device program."""
        timeout = float(_flags.get_flag("serving_tick_timeout_s"))
        if timeout <= 0:
            _chaos.maybe_delay("serving.harvest")
            return np.asarray(handle)
        box: dict = {}

        def work():
            try:
                _chaos.maybe_delay("serving.harvest")
                box["out"] = np.asarray(handle)
            except BaseException as e:  # noqa: BLE001 - forwarded below
                box["exc"] = e

        t = threading.Thread(target=work, name="serving-harvest",
                             daemon=True)
        t.start()
        t.join(timeout)
        if t.is_alive():
            raise TickTimeout(
                f"tick harvest did not materialize within "
                f"FLAGS_serving_tick_timeout_s={timeout}s — device "
                "program hung or wedged")
        if "exc" in box:
            raise box["exc"]
        return box["out"]

    def _error_evict(self, slot: int, error: str) -> None:
        """Terminal error for one RUNNING slot: trace outcome=error,
        evict (blocks released through the single accounting path),
        close the SSE stream with an error frame."""
        req = self.slot_req[slot]
        if req is None:
            return
        self._flightrec().record_event(
            "slot_error", slot=slot, rid=req.rid, error=error[:200])
        if req._prefilling:
            self._abort_prefill(req, outcome="error")
            return
        self._terminal_trace(req, "error")
        self._evict(slot)
        req._stream_push(None)

    def _strike(self, req: Request, error: str) -> None:
        """One admission-stage poison strike: the request's own program
        raised (prefill dispatch) or its prefill logits went
        non-finite.  First strike re-queues it at the BACK of the
        waiting queue (one more chance — transient-looking failures
        already consumed the in-place retries); at ``_POISON_STRIKES``
        it is quarantined: rejected ``reason=poisoned`` so it stops
        re-crashing every scheduler boundary."""
        req._strikes += 1
        if req._strikes >= self._POISON_STRIKES or req.cancelled \
                or self._draining:
            self.poisoned_requests += 1
            _M_POISONED.inc()
            _M_REJECTIONS.inc(reason="poisoned")
            req.outcome = "poisoned"
            if _metrics.enabled():
                self._reject_trace(req, "poisoned")
            self._flightrec().record_event(
                "poison_quarantine", rid=req.rid, strikes=req._strikes,
                error=error[:200])
            self.finished.append(req)
            req._stream_push(None)
        else:
            self.waiting.append(req)
        self._update_pressure()

    def _abandon(self, pend) -> None:
        """Consume an in-flight tick that will never be harvested (the
        tick-failure path): block BRIEFLY so its device writes finish
        before the implicated blocks are released for reallocation;
        errors and a still-running program past the grace period are
        swallowed — the slots are being evicted anyway."""
        try:
            h = pend.toks
            t = threading.Thread(
                target=lambda: jax.block_until_ready(h), daemon=True)
            t.start()
            t.join(1.0)
        except Exception:  # noqa: BLE001 - best-effort drain
            pass

    def _absorb_failure(self, exc: BaseException, pends) -> bool:
        """The crash-only tick guard's decision point.  Returns True
        when the failure was absorbed (the loop continues), False when
        it must propagate (sanitizer findings stay loud — a swallowed
        JaxsanError would defeat the sanitizer).

        A failure tagged with ``_serving_req`` (admission-stage: the
        request's own prefill/chunk program raised, or its logits went
        non-finite) strikes THAT request — the rest of the batch never
        notices.  Anything else is a tick-level failure: the in-flight
        ticks are abandoned and exactly the slots they covered are
        evicted ``outcome=error`` (attribution is program-granular —
        a whole-batch tick program names no slot)."""
        if isinstance(exc, _jaxsan.JaxsanError):
            return False
        self.tick_errors += 1
        _M_TICK_ERRORS.inc()
        err = f"{type(exc).__name__}: {exc}"[:200]
        req = getattr(exc, "_serving_req", None)
        self._flightrec().record_event(
            "tick_error", error=err,
            scope="request" if req is not None else "tick",
            rid=getattr(req, "rid", None))
        if req is not None:
            self._strike(req, err)
            return True
        slots = set()
        for p in pends:
            if p is None:
                continue
            self._abandon(p)
            slots.update(p.active)
        if not slots:
            slots = set(s for s in range(self.B)
                        if self.slot_req[s] is not None)
        for slot in sorted(slots):
            r = self.slot_req[slot]
            if r is None:
                continue
            if r.done:
                self._evict(slot)
            else:
                self._error_evict(slot, err)
        self._last_harvest_t = None
        self._update_occupancy()
        return True

    def _try_admit(self) -> bool:
        if not self.waiting or not self.free_slots:
            return False
        self._promote_waiting()
        req = self.waiting[0]
        L = len(req.prompt_ids)
        chunked = self.chunk > 0
        # --- prefix lookup: the longest resident full-block prefix is a
        # pointer copy; reuse is capped at L-1 so at least one suffix
        # token runs forward (its logits are the request's first token).
        # The cap makes copy-on-write exactly the fully-cached aligned
        # case: the last prompt token must be recomputed INTO a block the
        # index still shares.
        chain: List[int] = []
        cached_len = 0
        match = None
        if self.prefix is not None:
            # a deferred request retries every loop iteration: cache its
            # lookup across retries (the hash chain is O(prompt)) —
            # valid only within the index epoch, since an eviction could
            # free-and-reallocate a matched block under us
            match = getattr(req, "_prefix_match", None)
            if match is None \
                    or getattr(req, "_prefix_epoch", -1) \
                    != self.prefix.epoch:
                match = self.prefix.lookup(req.prompt_ids)
                req._prefix_match = match
                req._prefix_epoch = self.prefix.epoch
            chain = match.blocks
            cached_len = min(len(chain) * self.bs, L - 1)
            if cached_len <= 0:
                chain, cached_len = [], 0
        split_col = cached_len // self.bs
        cow = bool(chain) and (cached_len % self.bs != 0)
        if chain or chunked:
            # exact blocks for the real prompt span: suffix/chunk writes
            # go through PagedChunkView, whose padded positions route to
            # the pad block — no bucket over-allocation to release
            need_now = self._blocks_for(L) - split_col
        else:
            L_pad = self._pad_bucket(L)
            need_now = self._blocks_for(L_pad)  # <= nb_per_seq by clamp
        # full reservation: prompt blocks now + growth to the worst case
        total_need = self._blocks_for(L + req.max_new_tokens)
        growth = max(0, total_need - self._blocks_for(L))
        # pin the reused blocks BEFORE any index eviction can run: a
        # chain entry freed and reallocated under us would alias garbage
        for b in chain[:split_col]:
            self._ref_block(b)
        cow_src = chain[split_col] if cow else None
        if cow_src is not None:
            self._ref_block(cow_src)

        def unpin():
            for b in chain[:split_col]:
                self._release_block(b)
            if cow_src is not None:
                self._release_block(cow_src)

        short = need_now + growth - (len(self.free_blocks) - self.reserved)
        if short > 0 and self.prefix is not None:
            # pool pressure: orphaned index blocks are reclaimable —
            # evict leaf entries (LRU) until the admission fits or
            # nothing evictable remains.  Entries whose block is still
            # table-referenced are skipped (freeing them gains nothing
            # and would only cold-start a hot prefix)
            self.prefix.evict(short, self._release_block,
                              lambda b: int(self.block_rc[b]) == 1)
            short = need_now + growth \
                - (len(self.free_blocks) - self.reserved)
        if short > 0:
            unpin()
            # admission deferred on a drained pool: counted ONCE per
            # request so rejected/stalled traffic is diagnosable from the
            # metrics snapshot alone (the request stays queued and admits
            # when evictions return blocks)
            if not getattr(req, "_deferral_counted", False):
                req._deferral_counted = True
                _M_REJECTIONS.inc(reason="pool_exhausted")
            return False
        self.waiting.popleft()
        # admission starts NOW: everything before this point was queue
        # wait (incl. pool-exhausted deferrals — the tail /metrics must
        # surface under overload)
        t_admit = time.perf_counter() if _metrics.enabled() else None
        slot = self.free_slots.popleft()
        blocks = [self._alloc_block() for _ in range(need_now)]
        table_row = np.zeros((self.nb_per_seq,), np.int32)
        for col, b in enumerate(chain[:split_col]):
            table_row[col] = b
        for i, b in enumerate(blocks):
            table_row[split_col + i] = b
        req._growth_left = growth
        self.reserved += growth
        if chunked:
            # chunked admission: the prompt is absorbed between decode
            # ticks by the per-tick scheduler, not here
            return self._begin_chunked(req, slot, table_row, chain,
                                       split_col, cow_src, cached_len,
                                       t_admit)
        self.tables[slot, :] = table_row

        try:
            with self._params_for_call() as param_vals:
                # spec-decode engines thread (draft_params, draft_pools)
                # through admission so the draft model's prompt KV lands
                # in its pools via the same table row / block ids
                dpref = ((self._draft_vals(), self.pools, self.dpools)
                         if self.spec_model else (self.pools,))
                if chain:
                    if cow_src is not None:
                        # the shared block holds the cached positions of
                        # the last prompt block; copy it so the suffix
                        # write lands in a private block
                        cow_args = ((self.pools, self.dpools)
                                    if self.spec_model else (self.pools,))
                        out = self._cow_program()(
                            *cow_args, jnp.int32(cow_src),
                            jnp.int32(self.tables[slot, split_col]))
                        if self.spec_model:
                            self.pools, self.dpools = out
                        else:
                            self.pools = out
                        dpref = ((dpref[0], self.pools, self.dpools)
                                 if self.spec_model else (self.pools,))
                    Ls = L - cached_len
                    L_pad_s = self._pad_bucket(Ls)
                    suffix = np.zeros((1, L_pad_s), np.int32)
                    suffix[0, :Ls] = req.prompt_ids[cached_len:]
                    # private table-row copy: same R002 aliasing contract
                    # as the full-prefill call below
                    out = self._dispatch_call(
                        "serving.prefill.dispatch",
                        lambda: self._prefill_cont_program(L_pad_s)(
                            param_vals, *dpref,
                            jnp.asarray(
                                self.tables[slot:slot + 1].copy()),
                            jnp.asarray(suffix), jnp.int32(Ls),
                            jnp.int32(cached_len)))
                else:
                    prompt = np.zeros((1, L_pad), np.int32)
                    prompt[0, :L] = req.prompt_ids
                    # the table row must be a PRIVATE copy (graft-lint
                    # R002): jnp.asarray of the numpy view aliases
                    # zero-copy, and both the error path and the
                    # pad-block release below mutate self.tables before
                    # np.asarray(row) syncs — an in-flight prefill would
                    # read the mutated block ids
                    out = self._dispatch_call(
                        "serving.prefill.dispatch",
                        lambda: self._prefill_program(L_pad)(
                            param_vals, *dpref,
                            jnp.asarray(
                                self.tables[slot:slot + 1].copy()),
                            jnp.asarray(prompt), jnp.int32(L)))
                if self.spec_model:
                    row, self.pools, self.dpools = out
                else:
                    row, self.pools = out
                # host-sync + NaN screen BEFORE the prefix registers
                # anything (a poisoned prompt must not enter the index)
                row = self._screen_row(row, slot, req)
        except BaseException as e:
            # admission failed mid-flight: undo every host-side draw so
            # nothing leaks (references dropped — shared blocks survive
            # their other holders — slot freed, growth reservation
            # returned); the request is dropped from the queue and the
            # error propagates, tagged with the request so the tick
            # guard can strike/quarantine it instead of dying
            for col in range(self.nb_per_seq):
                if self.tables[slot, col]:
                    self._release_block(int(self.tables[slot, col]))
                    self.tables[slot, col] = 0
            if cow_src is not None:
                self._release_block(cow_src)
            self.free_slots.appendleft(slot)
            self.reserved -= growth
            req._growth_left = 0
            _M_REJECTIONS.inc(reason="error")
            try:
                e._serving_req = req
            except Exception:   # exotic exception types without a dict
                pass
            raise
        if cow_src is not None:
            self._release_block(cow_src)   # copy dispatched; pin over
        if not chain:
            # release pad-bucket blocks beyond the prompt's real span
            # (their stale contents are masked by seq_lens and
            # overwritten by any future owner before becoming visible)
            keep = self._blocks_for(L)
            for col in range(keep, need_now):
                self._release_block(int(self.tables[slot, col]))
                self.tables[slot, col] = 0
        if self.prefix is not None:
            # register this prompt's full blocks as shareable: reused
            # entries are touched, new full-block columns become entries
            # (one index reference each).  Registered blocks are never
            # written again: decode starts at position L, which lives in
            # an unregistered (partial or fresh) column.
            fullb = L // self.bs
            self.prefix.register(
                req.prompt_ids,
                [int(self.tables[slot, c]) for c in range(fullb)],
                self._ref_block, match=match)
            shared = split_col + (1 if cow_src is not None else 0)
            req._prefix_blocks = shared
            if chain:
                self.prefix.hits += 1
                _M_PREFIX_HITS.inc()
                self.prefix.blocks_shared += shared
                if shared:
                    _M_PREFIX_SHARED.inc(shared)
            else:
                self.prefix.misses += 1
                _M_PREFIX_MISSES.inc()
            # checksum the just-registered blocks (ground truth now;
            # immutable from here) — no-op unless blocksan is armed
            _jaxsan.blocksan_snapshot(self)
        self._finish_admission(req, slot, row, t_admit)
        return True

    def _finish_admission(self, req, slot, row, t_admit) -> None:
        """Shared admission tail (monolithic and chunked): host-sync the
        prefill logits into the first token, stamp queue-wait/TTFT, and
        activate the slot for decode ticks."""
        L = len(req.prompt_ids)
        _M_ADMISSIONS.inc()
        first = req._sample(np.asarray(row))
        if t_admit is not None:
            # np.asarray(row) above was the host sync: the first token
            # really exists now, so this is TTFT, not enqueue time
            t_first = time.perf_counter()
            req._t_admit, req._t_first = t_admit, t_first
            req._t_last = t_first
            if req._t_enqueue is not None:
                qwait = t_admit - req._t_enqueue
                ttft = t_first - req._t_enqueue
                _M_QWAIT.observe(qwait)
                _M_TTFT.observe(ttft)
                slo = _flags.get_flag("serving_ttft_slo_ms")
                if slo > 0 and ttft * 1e3 > slo:
                    _M_SLO.inc(metric="ttft")
        # router evidence (always on, unlike the metrics-gated sketches
        # above): the /healthz TTFT predictor needs admission rate and
        # recent TTFTs even on engines running with metrics disabled
        t_now = req._t_first if req._t_first is not None \
            else time.perf_counter()
        self._admit_times.append(t_now)
        t_enq = getattr(req, "_t_enqueue_ev", None)
        if t_enq is not None:
            ttft_ev = t_now - t_enq
            self._ttft_recent.append(ttft_ev)
            # always-on TTFT-SLO violation tally: the fleet burn-rate
            # monitor's "bad event" input (the metrics-gated twin above
            # feeds the scrape counter)
            slo_ev = _flags.get_flag("serving_ttft_slo_ms")
            if slo_ev > 0 and ttft_ev * 1e3 > slo_ev:
                self._ev_slo_viol += 1
        req.output_ids.append(first)
        req._stream_push(first)
        req.slot = slot
        self.slot_req[slot] = req
        self.seq_lens[slot] = L
        self.last_tok[slot] = first
        self.samp_do[slot] = req.do_sample
        self.samp_temp[slot] = req.temperature
        self.samp_topk[slot] = max(0, int(req.top_k))
        self.samp_topp[slot] = req.top_p
        self.samp_seed[slot] = np.uint32(req.seed & 0xFFFFFFFF)
        self.tok_pos[slot] = len(req.output_ids)
        self.tokens_out += 1
        _M_TOKENS.inc()
        self._update_occupancy()
        self._maybe_finish(req, first)

    def _free_capacity(self) -> int:
        """Free blocks INCLUDING those held only by the prefix index —
        the allocator reclaims them on demand (index eviction), so every
        observability surface (stats, the pool gauge, flight records)
        reports the same number: what an admission could actually get."""
        free = len(self.free_blocks)
        if self.prefix is not None:
            free += self.prefix.reclaimable(self.block_rc)
        return free

    def _update_occupancy(self):
        _M_POOL.set(round(1.0 - self._free_capacity()
                          / max(self.num_blocks, 1), 4))
        _M_SLOTS.set(round(1.0 - len(self.free_slots) / max(self.B, 1), 4))
        self._update_pressure()

    def _update_pressure(self):
        # registered scheduler-pressure gauges (ISSUE 6 satellite): the
        # exporter shows queue depth without calling into the engine
        running = self.B - len(self.free_slots)
        _M_RUNNING.set(running)
        _M_WAITING.set(len(self.waiting))
        _M_QUEUE_DEPTH.set(running + len(self.waiting))

    def _maybe_finish(self, req: Request, tok: int):
        if req.done:
            return
        if (req.eos_token_id is not None and tok == req.eos_token_id) or \
                len(req.output_ids) >= req.max_new_tokens:
            req.done = True
            req.outcome = "finished"
            self._ev_note("finished")
            self._ev_finished += 1
            self._ev_finished_tokens += len(req.output_ids)
            req._stream_push(None)      # close the SSE token stream
            # _t_first may lag _t_enqueue if the metrics gate flipped
            # between enqueue and admission; trace only complete timelines
            if _metrics.enabled() and req._t_enqueue is not None \
                    and req._t_first is not None:
                self._finish_trace(req)

    def _finish_trace(self, req: Request) -> None:
        """Request reached its terminal token: close the lifecycle trace
        — e2e into the sketch, the per-request record into the flight
        ring (post-mortem) and the /requests export ring (scrape)."""
        t = time.perf_counter()
        e2e = t - req._t_enqueue
        _M_E2E.observe(e2e)
        n_out = len(req.output_ids)
        rec = {"rid": req.rid, "outcome": "finished",
               "prompt_len": len(req.prompt_ids), "tokens_out": n_out,
               "ticks": req._ticks,
               "queue_wait_s": round(req._t_admit - req._t_enqueue, 6),
               "prefill_s": round(req._t_first - req._t_admit, 6),
               "ttft_s": round(req._t_first - req._t_enqueue, 6),
               "tpot_mean_s": round((t - req._t_first)
                                    / max(n_out - 1, 1), 6),
               "e2e_s": round(e2e, 6),
               "prefix_blocks": req._prefix_blocks,
               "prefill_chunks": req._prefill_chunks,
               **req._trace_ctx()}
        if self.spec:
            rec["spec_accept_rate"] = round(
                req._spec_accepted / max(req._spec_proposed, 1), 4)
            rec["spec_draft"] = self.spec_kind
        req.trace = rec
        self._flightrec().record_event("request", **rec)
        _export.record_request(rec)

    def _evict(self, slot: int):
        req = self.slot_req[slot]
        # return the part of the growth reservation this request never
        # drew (early eos); drawn blocks were decremented at allocation
        self.reserved -= getattr(req, "_growth_left", 0)
        req._growth_left = 0
        for col in range(self.nb_per_seq):
            if self.tables[slot, col]:
                # drop the table reference; blocks shared with the
                # prefix index (or another slot) survive the eviction
                self._release_block(int(self.tables[slot, col]))
                self.tables[slot, col] = 0
        self.seq_lens[slot] = 0
        self.last_tok[slot] = 0
        self.samp_do[slot] = False
        self.samp_temp[slot] = 1.0
        self.samp_topk[slot] = 0
        self.samp_topp[slot] = 1.0
        self.samp_seed[slot] = 0
        self.tok_pos[slot] = 0
        self.slot_req[slot] = None
        self.free_slots.append(slot)
        self.finished.append(req)
        self._update_occupancy()

    def _active_slots(self):
        # a slot mid-chunked-prefill is occupied but NOT decodable: its
        # seq_len stays 0 (the tick treats the row as inert) and its
        # table row stays all-zero until the last chunk installs it
        return [s for s in range(self.B)
                if self.slot_req[s] is not None
                and not self.slot_req[s]._prefilling]

    # -------------------------------------- per-tick scheduler (ISSUE 11)
    def _boundary_schedule(self) -> None:
        """The scheduler work of one REAL tick boundary.

        Order of business: propagate cancellations (waiting -> dropped,
        mid-prefill -> aborted, running -> evicted with blocks
        released), shed SLO-doomed arrivals, then admit.  Legacy mode
        (``FLAGS_serving_prefill_chunk`` = 0) keeps the historical
        admit-then-evict order and whole-prompt admissions.  Chunked
        mode budgets the boundary as "up to
        ``FLAGS_serving_prefill_chunks_per_tick`` chunk programs":
        finish the oldest in-flight prefill first, then begin new
        admissions — so every running stream's inter-token gap is
        bounded by (chunk budget x one chunk) + one decode tick no
        matter how long the arriving prompts are."""
        for slot in list(range(self.B)):
            req = self.slot_req[slot]
            if req is None or not req.cancelled:
                continue
            if req._prefilling:
                self._abort_prefill(req, outcome="cancelled")
            elif not req.done:
                self._terminal_trace(req, "cancelled")
                self._evict(slot)
                req._stream_push(None)
        if self.waiting and any(r.cancelled for r in self.waiting):
            kept = deque()
            for r in self.waiting:
                if r.cancelled:
                    self._terminal_trace(r, "cancelled")
                    self.finished.append(r)
                    r._stream_push(None)
                else:
                    kept.append(r)
            self.waiting = kept
            self._update_pressure()
        self._shed_waiting()
        if self.chunk <= 0:
            while self._try_admit():
                pass
            self._evict_done()
            return
        # chunked: evict finished FIRST — their slots and blocks fund
        # this boundary's chunk budget
        self._evict_done()
        budget = max(1, int(_flags.get_flag(
            "serving_prefill_chunks_per_tick")))
        if _flags.get_flag("serving_chunks_per_tick_auto"):
            budget = self._auto_chunk_budget(budget)
        spent = 0
        while spent < budget:
            if self.prefilling:
                req = self.prefilling[0]
                self._prefill_chunk_step(req)
                if not req._prefilling and self.prefilling \
                        and self.prefilling[0] is req:
                    self.prefilling.popleft()
                spent += 1
                continue
            # beginning an admission is host-only bookkeeping (+ at
            # most one CoW copy) — it costs no chunk budget; its first
            # chunk, dispatched by the next loop pass, does
            if not self._try_admit():
                break

    def _auto_chunk_budget(self, max_budget: int) -> int:
        """Live chunks-per-tick controller (ISSUE 17 satellite,
        FLAGS_serving_chunks_per_tick_auto): walk the budget one step at
        a time inside [1, FLAGS_serving_prefill_chunks_per_tick] from
        the always-on tick-level TPOT sketch against the TPOT SLO.
        Running p90 over target -> spend fewer chunk programs per
        boundary (decode gaps shrink); p90 under half the target ->
        spend more (prompts absorb faster).  No SLO or too little
        evidence: hold.  Only the BUDGET moves — which chunk programs
        exist is fixed at construction, so the warmup grid and program
        signatures never change."""
        cur = self._chunk_budget_now
        if cur is None:
            cur = max_budget
        cur = min(cur, max_budget)          # flag lowered at runtime
        target_ms = float(_flags.get_flag("serving_tpot_slo_ms"))
        if target_ms > 0 and self._ev_tpot.count >= 16:
            p90 = self._ev_tpot.quantile(0.9)
            if p90 is not None:
                if p90 * 1e3 > target_ms:
                    cur = max(1, cur - 1)
                elif p90 * 1e3 < 0.5 * target_ms:
                    cur = min(max_budget, cur + 1)
        self._chunk_budget_now = cur
        return cur

    def _evict_done(self) -> None:
        for slot in list(range(self.B)):
            req = self.slot_req[slot]
            if req is not None and not req._prefilling and req.done:
                self._evict(slot)

    def _promote_waiting(self) -> None:
        """Move the highest-priority waiting request (FIFO within a
        priority) to the queue head.  All-equal priorities keep strict
        FIFO — the head stays put and legacy behavior is unchanged."""
        if len(self.waiting) < 2:
            return
        best = 0
        for i in range(1, len(self.waiting)):
            if self.waiting[i].priority > self.waiting[best].priority:
                best = i
        if best:
            req = self.waiting[best]
            del self.waiting[best]
            self.waiting.appendleft(req)

    def _slo_breached(self) -> bool:
        """Are the LIVE p99 sketches over a configured SLO?  Shed
        decisions consult observed violation, not a prediction; with
        metrics off the sketches are empty and nothing ever sheds."""
        ttft_slo = _flags.get_flag("serving_ttft_slo_ms")
        if ttft_slo > 0 and _M_TTFT.count() \
                and _M_TTFT.quantile(0.99) * 1e3 > ttft_slo:
            return True
        tpot_slo = _flags.get_flag("serving_tpot_slo_ms")
        if tpot_slo > 0 and _M_TPOT.count() \
                and _M_TPOT.quantile(0.99) * 1e3 > tpot_slo:
            return True
        return False

    def _shed_waiting(self) -> None:
        """SLO-aware load shedding (``FLAGS_serving_slo_shed``): while
        the engine is ALREADY violating its latency targets and the
        waiting queue is deeper than the watermark, reject the newest
        lowest-priority waiting requests (reason=slo_shed) instead of
        queueing them into certain violations.  Consulted inputs: the
        live TTFT/TPOT p99 sketches + queue depth — not just pool
        capacity."""
        if not self.waiting or not _flags.get_flag("serving_slo_shed"):
            return
        depth = int(_flags.get_flag("serving_shed_queue_depth"))
        if len(self.waiting) <= depth or not self._slo_breached():
            return
        while len(self.waiting) > depth:
            # victim: lowest priority; newest within a priority (the
            # oldest requests keep their queue-time investment)
            victim = len(self.waiting) - 1
            for i in range(len(self.waiting) - 2, -1, -1):
                if self.waiting[i].priority \
                        < self.waiting[victim].priority:
                    victim = i
            req = self.waiting[victim]
            del self.waiting[victim]
            req.shed = True
            self.slo_sheds += 1
            _M_SLO_SHEDS.inc()
            _M_REJECTIONS.inc(reason="slo_shed")
            if _metrics.enabled():
                self._reject_trace(req, "slo_shed")
            self.finished.append(req)
            req._stream_push(None)
        self._update_pressure()

    def _begin_chunked(self, req, slot, row, chain, split_col, cow_src,
                       cached_len, t_admit) -> bool:
        """Chunked-prefill admission: stash the allocated table row on
        the REQUEST (a shadow row — ``self.tables[slot]`` stays
        all-zero, so decode ticks dispatched mid-prefill route the
        slot's inert seq_len-0 writes to the pad block instead of
        corrupting freshly written chunks), dispatch the CoW copy if a
        shared block must receive suffix writes, and queue the request
        for the per-tick chunk budget."""
        if cow_src is not None:
            try:
                cow_args = ((self.pools, self.dpools) if self.spec_model
                            else (self.pools,))
                out = self._cow_program()(
                    *cow_args, jnp.int32(cow_src),
                    jnp.int32(int(row[split_col])))
                if self.spec_model:
                    self.pools, self.dpools = out
                else:
                    self.pools = out
            except BaseException:
                for b in row:
                    if b:
                        self._release_block(int(b))
                self._release_block(cow_src)          # the pin
                self.free_slots.appendleft(slot)
                self.reserved -= req._growth_left
                req._growth_left = 0
                _M_REJECTIONS.inc(reason="error")
                raise
            self._release_block(cow_src)   # copy dispatched; pin over
        req.slot = slot
        req._chunk_row = row
        req._chunk_off = cached_len
        req._chunk_t_admit = t_admit
        req._prefilling = True
        req._prefill_chunks = 0
        self.slot_req[slot] = req
        self.prefilling.append(req)
        if self.prefix is not None:
            req._prefix_blocks = split_col + (1 if cow_src is not None
                                              else 0)
            if chain:
                self.prefix.hits += 1
                _M_PREFIX_HITS.inc()
                self.prefix.blocks_shared += req._prefix_blocks
                if req._prefix_blocks:
                    _M_PREFIX_SHARED.inc(req._prefix_blocks)
            else:
                self.prefix.misses += 1
                _M_PREFIX_MISSES.inc()
        self._update_occupancy()
        return True

    def _prefill_chunk_step(self, req) -> None:
        """Dispatch ONE bounded prefill chunk for an in-flight chunked
        admission: suffix tokens [off, off+n) padded to their ladder
        bucket through the suffix-prefill program (``start`` = off is a
        traced scalar — zero new programs, bit-identical writes and
        offset causal mask).  The LAST chunk's logits row is the
        request's first token."""
        slot = req.slot
        L = len(req.prompt_ids)
        off = req._chunk_off
        n = min(self.chunk, L - off)
        L_pad = self._pad_bucket(n)
        suffix = np.zeros((1, L_pad), np.int32)
        suffix[0, :n] = req.prompt_ids[off:off + n]
        t_c0 = time.perf_counter() if _metrics.enabled() else None
        try:
            with self._params_for_call() as param_vals:
                dpref = ((self._draft_vals(), self.pools, self.dpools)
                         if self.spec_model else (self.pools,))
                # private row copy: same R002 aliasing contract as the
                # monolithic prefill's table-row argument
                out = self._dispatch_call(
                    "serving.prefill.dispatch",
                    lambda: self._prefill_cont_program(L_pad)(
                        param_vals, *dpref,
                        jnp.asarray(req._chunk_row[None, :].copy()),
                        jnp.asarray(suffix), jnp.int32(n),
                        jnp.int32(off)))
            if self.spec_model:
                row, self.pools, self.dpools = out
            else:
                row, self.pools = out
            if req._chunk_off + n >= L:
                # last chunk: host-sync + NaN screen before the shadow
                # row installs and the prefix registers (same contract
                # as the monolithic path's _screen_row placement)
                row = self._screen_row(row, slot, req)
        except BaseException as e:
            self._abort_prefill(req)
            _M_REJECTIONS.inc(reason="error")
            try:
                e._serving_req = req
            except Exception:
                pass
            raise
        if t_c0 is not None:
            # host-side chunk dispatch time (async enqueue; a sampled
            # chunk program blocks inside the call) — the boundary's
            # chunk-prefill phase in the tick record
            # graft-lint: disable=R006
            self._chunk_s_this_boundary += time.perf_counter() - t_c0
        req._chunk_off = off + n
        req._prefill_chunks += 1
        self.prefill_chunks_total += 1
        self._chunks_this_boundary += 1
        _M_PREFILL_CHUNKS.inc()
        if _metrics.enabled():
            self._flightrec().record_event(
                "prefill_chunk", rid=req.rid, slot=slot, start=off,
                tokens=n, done=req._chunk_off >= L)
        if req._chunk_off >= L:
            self._complete_chunked(req, row)

    def _complete_chunked(self, req, row) -> None:
        """Last chunk landed: install the shadow table row (the slot
        becomes decodable), register the prompt's full blocks in the
        prefix index — registration HAD to wait, chunk c+1 still writes
        blocks chunk c filled and registered blocks are immutable —
        and run the shared admission tail."""
        slot = req.slot
        L = len(req.prompt_ids)
        self.tables[slot, :] = req._chunk_row
        req._prefilling = False
        req._chunk_row = None
        if self.prefix is not None:
            fullb = L // self.bs
            self.prefix.register(
                req.prompt_ids,
                [int(self.tables[slot, c]) for c in range(fullb)],
                self._ref_block,
                match=getattr(req, "_prefix_match", None))
            _jaxsan.blocksan_snapshot(self)
        self._finish_admission(req, slot, row, req._chunk_t_admit)

    def _abort_prefill(self, req, outcome: Optional[str] = None) -> None:
        """Tear down a mid-chunked-prefill admission: release every
        shadow-row block reference (shared blocks survive their other
        holders), return the slot and the growth reservation.  With
        ``outcome`` (cancellation) the request also gets a terminal
        trace and lands in ``finished``."""
        slot = req.slot
        for b in req._chunk_row:
            if b:
                self._release_block(int(b))
        req._chunk_row = None
        req._prefilling = False
        self.reserved -= req._growth_left
        req._growth_left = 0
        self.slot_req[slot] = None
        self.free_slots.appendleft(slot)
        req.slot = None
        try:
            self.prefilling.remove(req)
        except ValueError:
            pass
        if outcome is not None:
            self._terminal_trace(req, outcome)
            self.finished.append(req)
            req._stream_push(None)
        self._update_occupancy()

    def _terminal_trace(self, req, outcome: str) -> None:
        """Non-finish lifecycle endpoints (cancellations, errors,
        drains) get a trace record too, metrics-gated like everything
        else; the outcome itself is stamped unconditionally — the SSE
        terminal frame needs it regardless of the metrics gate."""
        req.outcome = outcome
        self._ev_note(outcome)
        if not _metrics.enabled():
            return
        rec = {"rid": req.rid, "outcome": outcome,
               "prompt_len": len(req.prompt_ids),
               "max_new_tokens": req.max_new_tokens,
               "tokens_out": len(req.output_ids),
               **req._trace_ctx()}
        req.trace = rec
        self._flightrec().record_event("request", **rec)
        _export.record_request(rec)

    def step(self) -> bool:
        """One SYNCHRONOUS scheduler tick: run the boundary schedule
        (evict finished, spend the admission/chunk budget), run one
        compiled decode tick over the current mix and harvest it.
        Returns True while work remains.  UNGUARDED — exceptions
        propagate to the caller; the serve loops wrap it (or their own
        cycles) in the crash-only guard."""
        pend = self._dispatch_tick(boundary=True)
        if pend is None:
            return bool(self.waiting or self.prefilling)
        self._harvest_tick(pend)
        return True

    def _guarded_step(self) -> bool:
        """`step()` under the crash-only guard: a dispatch/harvest
        failure is absorbed by `_absorb_failure` (request strike or
        implicated-slot eviction) and the loop stays alive; only
        sanitizer findings (JaxsanError) still propagate."""
        pend = None
        try:
            pend = self._dispatch_tick(boundary=True)
            if pend is None:
                return bool(self.waiting or self.prefilling)
            self._harvest_tick(pend)
            return True
        except Exception as e:  # noqa: BLE001 - the guard's whole job
            if not self._absorb_failure(e, (pend,)):
                raise
            return True

    def _dispatch_tick(self, boundary: bool = True, chain=None):
        """Launch one compiled decode tick and return it IN FLIGHT.

        At a tick ``boundary`` the scheduler work runs first (admit
        what fits, evict finished).  ``chain`` is the previous in-flight
        `_PendingTick` (the overlap path): its on-device outputs feed
        straight back in instead of the host arrays.  JAX async
        dispatch means the returned `_PendingTick.toks` is a device
        handle nothing has blocked on; host seq_lens/tok_pos advance
        NOW so a second dispatch sees the in-flight state."""
        timed = _metrics.enabled()
        ph_sched = ph_chunk = 0.0
        if boundary:
            t_b0 = time.perf_counter() if timed else 0.0
            self._chunk_s_this_boundary = 0.0
            self._boundary_schedule()
            if timed:
                # the boundary's host phases (ISSUE 14): chunk-prefill
                # dispatch time accumulated by _prefill_chunk_step,
                # everything else (cancel/shed/admit/evict) = schedule
                ph_chunk = self._chunk_s_this_boundary
                ph_sched = max(
                    0.0, time.perf_counter() - t_b0 - ph_chunk)
        active = self._active_slots()
        if not active:
            return None
        t0 = time.perf_counter()
        device_sampling = _flags.get_flag("serving_device_sampling")
        # a chained dispatch continues its predecessor's kind (the
        # overlap gate matched them); at a boundary, spec eligibility is
        # re-evaluated against the live budgets
        use_spec = (bool(chain.spec) if chain is not None
                    else self._spec_eligible(active, device_sampling))
        if use_spec:
            pend = self._dispatch_spec(active, t0, chain)
            pend.chunks = self._chunks_this_boundary
            self._chunks_this_boundary = 0
            pend.ph_sched, pend.ph_chunk = ph_sched, ph_chunk
            if timed:
                # host dispatch phase: enqueue cost by design (the
                # compute lands in the harvest wait; a sampled program
                # blocks inside the call) — graft-lint: disable=R006
                pend.ph_dispatch = time.perf_counter() - t0
            return pend
        k = self._tick_size(active)
        # ensure a physical block exists for every position this tick
        # will write (all draws covered by the admission reservation)
        for slot in active:
            for pos in range(int(self.seq_lens[slot]),
                             int(self.seq_lens[slot]) + k):
                col = pos // self.bs
                if pos % self.bs == 0 and self.tables[slot, col] == 0:
                    blk = self._alloc_block()
                    self.reserved -= 1
                    self.slot_req[slot]._growth_left -= 1
                    self.tables[slot, col] = blk
        # device inputs get PRIVATE host copies: async dispatch returns
        # before the program consumes them, and jax device_put may alias
        # numpy memory zero-copy — without the copy, this tick's own
        # post-dispatch bookkeeping (and any overlapped next tick's
        # block draws) would race the in-flight program's reads.  The
        # copy is routed through the jaxsan shield (a plain .copy() with
        # FLAGS_enable_jaxsan off): checksummed at dispatch, verified at
        # harvest, so reintroducing the aliasing bug fails loudly
        san = _jaxsan.token("serving.tick")
        dev = lambda a: jnp.asarray(_jaxsan.shield(san, a))  # noqa: E731
        last = chain.toks[:, -1] if chain is not None \
            else dev(self.last_tok)
        logits = None
        with self._params_for_call() as param_vals, \
                _flight.guard("serving.tick"):
            if not device_sampling and k == 1:
                # host-sampling fallback: the k=1 program returns the
                # logits the per-row host sampler needs
                greedy, logits, self.pools = self._dispatch_call(
                    "serving.tick.dispatch",
                    lambda: self._decode_program()(
                        param_vals, self.pools, dev(self.tables),
                        dev(self.seq_lens), last))
                toks = greedy[:, None]
            else:
                # the one k-step tick program; with sampling off the
                # demotion guarantees no sampled row is active, the
                # all-False mask takes the greedy cond branch
                toks, self.pools = self._dispatch_call(
                    "serving.tick.dispatch",
                    lambda: self._tick_program(k)(
                        param_vals, self.pools, dev(self.tables),
                        dev(self.seq_lens), last,
                        dev(self.samp_do), dev(self.samp_temp),
                        dev(self.samp_topk), dev(self.samp_topp),
                        dev(self.samp_seed), dev(self.tok_pos)))
        self.steps += k
        for slot in active:
            self.seq_lens[slot] += k
            self.tok_pos[slot] += k
        pend = _PendingTick(active=active, k=k, toks=toks, logits=logits,
                            reqs=list(self.slot_req), t0=t0,
                            device_sampling=device_sampling,
                            step_no=self.steps, san=san)
        pend.chunks = self._chunks_this_boundary
        self._chunks_this_boundary = 0
        pend.ph_sched, pend.ph_chunk = ph_sched, ph_chunk
        if timed:
            # host dispatch phase: enqueue cost by design (the compute
            # lands in the harvest wait; a sampled program blocks
            # inside the call) — graft-lint: disable=R006
            pend.ph_dispatch = time.perf_counter() - t0
        return pend

    def _spec_eligible(self, active, device_sampling) -> bool:
        """May this tick run draft/verify?  Needs the subsystem, on-
        device sampling (the host sampler cannot verify), and at least
        ONE active slot able to absorb more than a single token —
        eligibility is PER SLOT now (each slot carries its own emit cap
        into the program), so a short-budget slot merely rides capped
        instead of demoting the whole tick to the plain path.  Only a
        batch where nobody could beat the plain tick falls back."""
        if not self.spec or not device_sampling:
            return False
        need = min(2, self.spec_k_now)
        for slot in active:
            req = self.slot_req[slot]
            if req.max_new_tokens - int(self.tok_pos[slot]) >= need:
                return True
        return False

    # adaptive-k controller constants: step up while the acceptance
    # EWMA clears _ADAPT_UP (proposals are nearly free tokens — reach
    # further), down when it sinks under _ADAPT_DOWN (the verify chunk
    # is mostly wasted width), after at least _ADAPT_MIN_TICKS spec
    # ticks at the current rung (hysteresis against single-tick noise).
    _ADAPT_UP = 0.75
    _ADAPT_DOWN = 0.35
    _ADAPT_MIN_TICKS = 2
    _EWMA_BETA = 0.5

    def _adapt_step(self) -> int:
        """Ladder index delta the controller wants RIGHT NOW (+1 / -1 /
        0), from the live acceptance EWMA with hysteresis.  Split from
        the state change so `_can_overlap` can ask "is a step due?"
        without taking it — a chained dispatch reuses its
        predecessor's k, so while a step is due the overlap gate must
        force a real boundary or adaptation would never run for
        model-draft engines (their spec ticks chain indefinitely under
        the default overlap flag)."""
        if not self.spec_adaptive or self._accept_ewma is None \
                or self._spec_ticks_since_adapt < self._ADAPT_MIN_TICKS:
            return 0
        i = self.spec_ladder.index(self.spec_k_now)
        if self._accept_ewma >= self._ADAPT_UP \
                and i + 1 < len(self.spec_ladder):
            return 1
        if self._accept_ewma <= self._ADAPT_DOWN and i > 0:
            return -1
        return 0

    def _adapt_k(self) -> int:
        """Boundary-time adaptive-k step: move ``spec_k_now`` one rung
        along the ladder per decision, driven by the live acceptance
        EWMA (the same counters `stats()['speculative']` reports).
        Every rung's program is warmed, so a step never compiles."""
        step = self._adapt_step()
        if step:
            i = self.spec_ladder.index(self.spec_k_now)
            self.spec_k_now = self.spec_ladder[i + step]
            self.spec_k_switches += 1
            self._spec_ticks_since_adapt = 0
        return self.spec_k_now

    def _dispatch_spec(self, active, t0, chain=None):
        """Launch one speculative tick (proposal + verify) in flight.

        Proposals and verify both write positions ``seq..seq+k-1``;
        only the accepted prefix becomes durable — the rest is masked
        by seq_lens and overwritten by the next chunk (rollback by
        construction).  PER-SLOT eligibility: each slot's emit cap
        ``kcap = min(k, remaining budget)`` rides in as a device input;
        host seq_lens/tok_pos advance by that per-slot upper bound now
        (budget clamps and a chained dispatch's block coverage need a
        bound, not the truth) and harvest refunds the per-slot
        shortfall ``kcap - emitted``.  A chained MODEL-draft dispatch
        feeds the predecessor's on-device new_lens/new_last handles —
        the draft phase of tick t+1 runs in tick t's harvest bubble.
        Host-draft (ngram) ticks never chain: the next proposal needs
        the harvested tokens.  With ``FLAGS_serving_spec_adaptive`` an
        unchained dispatch first lets the controller step k along the
        warmed ladder."""
        k = chain.k if chain is not None else self._adapt_k()
        kcap = np.zeros((self.B,), np.int32)
        ineligible = 0
        for slot in active:
            req = self.slot_req[slot]
            cap = min(k, req.max_new_tokens - int(self.tok_pos[slot]))
            kcap[slot] = cap       # >= 1: eligibility/overlap gated it
            if cap < k:
                ineligible += 1
            base = int(self.seq_lens[slot])
            for pos in range(base, base + cap):
                col = pos // self.bs
                if pos % self.bs == 0 and self.tables[slot, col] == 0:
                    blk = self._alloc_block()
                    self.reserved -= 1
                    req._growth_left -= 1
                    self.tables[slot, col] = blk
        if ineligible:
            self.spec_ineligible_slots += ineligible
            _M_SPEC_INELIGIBLE.inc(ineligible)
        _M_SPEC_K.set(k)
        san = _jaxsan.token("serving.tick")
        dev = lambda a: jnp.asarray(_jaxsan.shield(san, a))  # noqa: E731
        if chain is not None:
            lens_in, last_in = chain.new_lens, chain.new_last
        else:
            lens_in, last_in = dev(self.seq_lens), dev(self.last_tok)
        samp = (dev(self.samp_do), dev(self.samp_temp),
                dev(self.samp_topk), dev(self.samp_topp),
                dev(self.samp_seed))
        with self._params_for_call() as param_vals, \
                _flight.guard("serving.tick"):
            if self.spec_model:
                toks, counts, accepts, new_lens, new_last, self.pools, \
                    self.dpools = self._dispatch_call(
                        "serving.tick.dispatch",
                        lambda: self._spec_program(k)(
                            param_vals, self._draft_vals(), self.pools,
                            self.dpools, dev(self.tables), lens_in,
                            last_in, *samp, dev(kcap)))
                self.steps += k + 1      # k draft forwards + one verify
            else:
                # host-side n-gram proposals (near-zero cost; the whole
                # draft "model" is a few dict probes per slot) ride in
                # as device inputs — the program is one verify forward
                dtoks = np.zeros((self.B, k), np.int32)
                for slot in active:
                    req = self.slot_req[slot]
                    if req._drafter is None:
                        from .drafting import NGramDraft
                        req._drafter = NGramDraft()
                    dtoks[slot] = req._drafter.propose_stream(
                        req.prompt_ids, req.output_ids, k)
                toks, counts, accepts, new_lens, new_last, self.pools \
                    = self._dispatch_call(
                        "serving.tick.dispatch",
                        lambda: self._spec_hd_program(k)(
                            param_vals, self.pools, dev(self.tables),
                            lens_in, last_in, dev(dtoks), *samp,
                            dev(kcap)))
                self.steps += 1          # one chunk verify forward
        for slot in active:
            self.seq_lens[slot] += int(kcap[slot])
            self.tok_pos[slot] += int(kcap[slot])
        pend = _PendingTick(active=active, k=k, toks=toks, logits=None,
                            reqs=list(self.slot_req), t0=t0,
                            device_sampling=True, step_no=self.steps,
                            san=san)
        pend.spec = True
        pend.counts = counts
        pend.accepts = accepts
        pend.new_lens = new_lens
        pend.new_last = new_last
        pend.kcap = kcap
        return pend

    def _harvest_tick(self, pend) -> None:
        """Block on the tick's device tokens and feed the requests:
        append, EOS/budget-check, host-sample (fallback path only).
        `pend.reqs` is the slot->request snapshot from dispatch time —
        under overlap a request may have finished (EOS) while its next
        tick was already in flight; its overrun rows are discarded."""
        k = pend.k
        timed = _metrics.enabled()
        t_h0 = time.perf_counter() if timed else 0.0
        with _flight.guard("serving.tick"):
            # first host block on the async result: a decode-execution
            # error (OOM, XlaRuntimeError) surfaces HERE, not at the
            # guarded dispatch — keep the post-mortem dump coverage.
            # The tick watchdog (FLAGS_serving_tick_timeout_s) bounds
            # this block: a hung device program raises TickTimeout
            # instead of wedging the loop forever.
            toks = self._materialize(pend.toks)
        # harvest-wait phase: the block above is where device compute
        # not yet finished is actually waited for
        t_wait_end = time.perf_counter() if timed else 0.0
        # the program has materialized: every host buffer fed at dispatch
        # must still hash to its dispatch-time checksum (jaxsan; no-op
        # unless FLAGS_enable_jaxsan)
        _jaxsan.verify(pend.san)
        logits_np = None
        bad_slots: dict = {}
        if not pend.spec and pend.logits is not None:
            # host-sampling decode path: the per-row logits are host-
            # visible, so NaN attribution is PER SLOT here — an armed
            # chaos injection or a real non-finite forward implicates
            # exactly one row (evicted outcome=error after the loop)
            logits_np, bad_slots = self._screen_decode_logits(pend)
        toks_before = self.tokens_out
        sampled = 0
        spec_accepted = 0
        spec_proposed = 0
        harvested_by: List = []   # (req, tokens harvested this tick)
        if pend.spec:
            # speculative tick: per-slot emitted counts (1..kcap) and
            # accepted-draft counts materialize with the tokens; refund
            # the dispatch-time PER-SLOT upper-bound advance (kcap per
            # slot) down to the true emitted length — relative, so it
            # composes with any further conservative advance already
            # applied by an overlapped next dispatch
            counts = np.asarray(pend.counts)
            accepts = np.asarray(pend.accepts)
            metrics_on = _metrics.enabled()
            for slot in pend.active:
                req = pend.reqs[slot]
                c = int(counts[slot])
                cap = int(pend.kcap[slot])
                self.seq_lens[slot] -= cap - c
                self.tok_pos[slot] -= cap - c
                if req.done:
                    continue     # whole row is EOS overrun
                n_before = len(req.output_ids)
                harvested_by.append((req, n_before))
                req._ticks += 1
                # acceptance accounts the full k proposals (the
                # drafter-quality signal the adaptive controller
                # consumes), independent of the slot's emit cap
                spec_proposed += k
                spec_accepted += int(accepts[slot])
                req._spec_proposed += k
                req._spec_accepted += int(accepts[slot])
                if metrics_on:
                    _M_SPEC_SLOT_ACC.set(
                        round(req._spec_accepted
                              / max(req._spec_proposed, 1), 4),
                        slot=slot)
                self.last_tok[slot] = int(toks[slot, c - 1])
                for j in range(c):
                    if req.done:
                        break    # post-eos tokens are discarded
                    tok = int(toks[slot, j])
                    if req.do_sample:
                        sampled += 1
                    req.output_ids.append(tok)
                    req._stream_push(tok)
                    self.tokens_out += 1
                    self._maybe_finish(req, tok)
            self.spec_ticks += 1
            self.spec_proposed += spec_proposed
            self.spec_accepted += spec_accepted
            if spec_proposed:
                _M_SPEC_PROPOSED.inc(spec_proposed)
                # the adaptive controller's evidence: tick-level accept
                # rate folded into a fast EWMA (consulted at boundary
                # dispatches by `_adapt_k`)
                rate = spec_accepted / spec_proposed
                self._accept_ewma = rate if self._accept_ewma is None \
                    else (self._EWMA_BETA * self._accept_ewma
                          + (1.0 - self._EWMA_BETA) * rate)
                self._spec_ticks_since_adapt += 1
            if spec_accepted:
                _M_SPEC_ACCEPTED.inc(spec_accepted)
        else:
            for slot in pend.active:
                req = pend.reqs[slot]
                if req.done:
                    continue     # whole row is EOS overrun
                if slot in bad_slots:
                    continue     # non-finite row: no tokens emitted;
                                 # the slot is evicted outcome=error
                                 # at the end of this harvest
                n_before = len(req.output_ids)
                harvested_by.append((req, n_before))
                req._ticks += 1
                self.last_tok[slot] = int(toks[slot, -1])
                for j in range(k):
                    if req.done:
                        break    # post-eos tokens are discarded (the
                                 # compiled tick keeps decoding; the
                                 # cache rows die with the eviction)
                    if req.do_sample and not pend.device_sampling:
                        if logits_np is None:
                            logits_np = np.asarray(pend.logits)
                        tok = req._sample(logits_np[slot])
                        self.last_tok[slot] = tok
                    else:
                        tok = int(toks[slot, j])
                    if req.do_sample:
                        sampled += 1
                    req.output_ids.append(tok)
                    req._stream_push(tok)
                    self.tokens_out += 1
                    self._maybe_finish(req, tok)
        # wall time ATTRIBUTABLE to this tick: an overlapped tick was
        # dispatched before the previous harvest finished, so clock it
        # from that harvest, not from its own dispatch — tick_seconds
        # then sum to real elapsed wall and tokens/sec stays honest
        t_done = time.perf_counter()
        t_from = pend.t0 if self._last_harvest_t is None \
            else max(pend.t0, self._last_harvest_t)
        self._last_harvest_t = t_done
        dt = t_done - t_from
        harvested = self.tokens_out - toks_before
        if harvested > 0 and dt > 0:
            # always-on tick-level TPOT evidence for the fleet telescope
            # (one harvest gap imputed to the k tokens it yielded) —
            # deliberately NOT per-request timing, so the "metrics off
            # = zero per-request tracing work" pin stays intact
            self._ev_tpot.add(dt / max(k, 1), weight=harvested)
        if _metrics.enabled():
            # per-token inter-token latency (TPOT): tokens arrive k at a
            # time, so each of this harvest's tokens is imputed an equal
            # share of the gap since the request's previous token
            tpot_slo = _flags.get_flag("serving_tpot_slo_ms")
            for req, n_before in harvested_by:
                n_new = len(req.output_ids) - n_before
                if n_new <= 0 or req._t_last is None:
                    continue
                gap = (t_done - req._t_last) / n_new
                req._t_last = t_done
                _M_TPOT.observe(gap, weight=n_new)
                if tpot_slo > 0 and gap * 1e3 > tpot_slo:
                    _M_SLO.inc(n_new, metric="tpot")
        self.ticks += 1
        _M_TICKS.inc()
        _M_TICK_S.observe(dt)
        _M_TOKENS.inc(harvested)
        if sampled:
            _M_SAMPLED.inc(sampled)
        if dt > 0:
            _M_TPS.set(round(harvested / dt, 1))
        self._update_occupancy()
        if _metrics.enabled():
            # the flight ring keeps the last-K ticks, so a post-mortem
            # dump of a wedged/crashed engine shows what was in flight
            # per-tick phase breakdown (ISSUE 14): dispatch-time host
            # phases stamped on the pend + the harvest wait (device) /
            # emit (host detokenize+stream) split measured here.  The
            # phases need not sum to wall_s: an overlapped tick's wall
            # clock starts at the previous harvest, and device compute
            # overlaps the host phases by design.
            # `timed` is the gate state at HARVEST ENTRY: a mid-tick
            # flag flip must not difference against zero stamps
            ph_wait = (t_wait_end - t_h0) if timed else 0.0
            ph_emit = (t_done - t_wait_end) if timed else 0.0
            rec = {
                "timeline": "serving", "step": pend.step_no,
                "t_unix": round(time.time(), 6),
                "wall_s": round(dt, 6), "decode_steps": k,
                "tokens": harvested, "overlap": pend.overlapped,
                "tokens_per_sec": round(harvested / dt, 1) if dt else 0.0,
                "active": len(pend.active), "waiting": len(self.waiting),
                "free_blocks": self._free_capacity(),
                "phases": {
                    "schedule_ms": round(pend.ph_sched * 1e3, 4),
                    "chunk_prefill_ms": round(pend.ph_chunk * 1e3, 4),
                    "dispatch_ms": round(pend.ph_dispatch * 1e3, 4),
                    "harvest_wait_ms": round(ph_wait * 1e3, 4),
                    "emit_ms": round(ph_emit * 1e3, 4),
                    "host_ms": round((pend.ph_sched + pend.ph_chunk
                                      + pend.ph_dispatch + ph_emit)
                                     * 1e3, 4),
                    "device_wait_ms": round(ph_wait * 1e3, 4)}}
            if pend.spec:
                rec["spec"] = True
                rec["spec_kind"] = self.spec_kind
                rec["spec_k"] = pend.k
                rec["spec_accepted"] = spec_accepted
            if pend.chunks:
                rec["prefill_chunks"] = pend.chunks
            tids = sorted({r.trace_id for r, _ in harvested_by
                           if r.trace_id})
            if tids:
                rec["trace_ids"] = tids
            self._flightrec().record_step(rec)
        # failure isolation (ISSUE 15): rows whose logits screened
        # non-finite are evicted HERE — outcome=error, blocks released
        # through the single accounting path — and every other slot's
        # stream is untouched (their tokens were already emitted above)
        for slot, err in bad_slots.items():
            self._error_evict(slot, err)
        # blocksan boundary reconciliation: the harvest is the one point
        # where no admission is mid-flight and every transient pin has
        # resolved — ledger vs tables/shadow rows/index, free-list
        # agreement, registered-block checksums (no-op when disarmed)
        _jaxsan.blocksan_verify(self)

    def _tick_size(self, active) -> int:
        """Steps this tick may batch: bounded by the configured tick
        size and every active request's remaining budget (over-decoding
        past a budget would outrun its block reservation).  Budgets
        count DISPATCHED tokens (`tok_pos`), so an overlapped in-flight
        tick is already accounted for.  With on-device sampling,
        sampled and greedy rows share the full k-step tick; the
        host-sampling fallback (FLAGS_serving_device_sampling=0)
        demotes any tick with a sampling request to k=1."""
        k = self.steps_per_tick
        device_sampling = _flags.get_flag("serving_device_sampling")
        for slot in active:
            req = self.slot_req[slot]
            if req.do_sample and not device_sampling:
                return 1
            k = min(k, req.max_new_tokens - int(self.tok_pos[slot]))
        # exactly two compiled variants: the full tick and the k=1 tail
        # (a mid-run compile of an intermediate size costs more than the
        # single steps it would save)
        return k if k >= self.steps_per_tick else 1

    def _can_overlap(self, pend) -> bool:
        """May tick t+1 dispatch before tick t (`pend`) is harvested?
        Requires the overlap flag, next-token choice living on device
        (host sampling owns it otherwise), no admissions pending (they
        join at a REAL boundary: their prefill must not race the
        in-flight tick's pool writes), and at least one budgeted token
        per active request beyond the in-flight tick (the block-budget
        clamp that keeps EOS overrun inside the reservation).  The
        chained dispatch continues `pend`'s KIND: a spec tick chains a
        spec tick (on the device seq_lens/last handles, needing spec_k
        budget beyond the in-flight upper bound), a plain tick a plain
        one — a kind switch is a real boundary (harvest first)."""
        if not _flags.get_flag("serving_overlap"):
            return False
        if self.waiting:
            return False     # admissions join at a real boundary
        if self.prefilling and not self._chunk_overlap_ok():
            return False     # pending chunk work needs a real boundary
        if pend.spec:
            if not self.spec_model:
                return False     # ngram proposals need the harvested
                                 # tokens: a host draft cannot chain
            if self._adapt_step():
                return False     # a k step is due: chained dispatches
                                 # reuse chain.k, so force a boundary
                                 # and let _adapt_k move the rung
            if not _flags.get_flag("serving_device_sampling"):
                return False     # mid-run flip: verify owns sampling
            for slot in pend.active:
                req = self.slot_req[slot]
                if req is None or req.done:
                    return False
                if req.max_new_tokens - int(self.tok_pos[slot]) < 1:
                    return False     # per-slot caps need >= 1 headroom
            # X-ray sampling contract (ISSUE 14): a due synced probe
            # must land on a REAL boundary — a chained dispatch feeds
            # the predecessor's device handles, so a probe around it
            # would time both ticks
            if _xray.sampling_on() \
                    and _xray.sample_due(self._spec_fns.get(pend.k)):
                return False
            return True
        if not pend.device_sampling and any(
                pend.reqs[s].do_sample for s in pend.active):
            return False
        if self.spec and self._spec_eligible(
                pend.active, _flags.get_flag("serving_device_sampling")):
            return False         # plain->spec switch (e.g. the sampling
                                 # flag flipped back on): boundary first
        for slot in pend.active:
            req = self.slot_req[slot]
            if req is None or req.done:
                return False     # eviction boundary needed first
            if req.max_new_tokens - int(self.tok_pos[slot]) < 1:
                return False     # in-flight tick exhausts the budget
        if _xray.sampling_on():
            # same sampling contract as the spec branch: the program a
            # chained dispatch would run must not be due a synced probe
            k = self._tick_size(pend.active)
            nxt = self._decode_fn if (k == 1 and not _flags.get_flag(
                "serving_device_sampling")) else self._tick_fns.get(k)
            if _xray.sample_due(nxt):
                return False
        return True

    def _chunk_overlap_ok(self) -> bool:
        """May pending chunk-prefill work ride BEHIND an overlapped
        tick instead of forcing a real boundary (the parked PR 11
        remainder, ``FLAGS_serving_chunk_overlap``)?  Only NON-FINAL
        chunks qualify: the final chunk host-syncs its logits row
        (`_screen_row`) and installs the shadow table row — boundary
        work by contract.  So the head chunked admission must still
        have more than one chunk of prompt left."""
        if self.chunk <= 0 \
                or not _flags.get_flag("serving_chunk_overlap"):
            return False
        req = self.prefilling[0]
        return len(req.prompt_ids) - req._chunk_off > self.chunk

    def _overlap_chunk_work(self, nxt) -> None:
        """Dispatch non-final prefill chunks for the head chunked
        admission BEHIND the just-chained tick ``nxt``: programs
        serialize in dispatch order on the device stream and each chunk
        consumes ``self.pools`` — by now the chained tick's output
        handle — so the chunk reads post-tick pool state exactly as a
        boundary dispatch would, while its host-side enqueue cost hides
        under the in-flight ticks.  Chunk writes land in the admission's
        own (not-yet-decodable) blocks, disjoint from every active
        slot's, so tick/chunk order commutes and token streams stay
        bit-identical with the flag off.  The FINAL chunk never runs
        here (see `_chunk_overlap_ok`); an armed X-ray sampler skips
        the path entirely — a synced probe around a chunk program
        would time the chained tick too."""
        if not self.prefilling or not self._chunk_overlap_ok() \
                or _xray.sampling_on():
            return
        budget = max(1, int(_flags.get_flag(
            "serving_prefill_chunks_per_tick")))
        req = self.prefilling[0]
        self._chunks_this_boundary = 0
        self._chunk_s_this_boundary = 0.0
        spent = 0
        while (spent < budget
               and len(req.prompt_ids) - req._chunk_off > self.chunk):
            self._prefill_chunk_step(req)
            spent += 1
            self.overlap_chunks_total += 1
        # fold the accounting into the chained tick's record: these
        # chunks belong to ITS dispatch window, not the next boundary's
        nxt.chunks += self._chunks_this_boundary
        nxt.ph_chunk += self._chunk_s_this_boundary
        self._chunks_this_boundary = 0
        self._chunk_s_this_boundary = 0.0

    def run(self) -> List[Request]:
        """Drive until every queued request finishes; returns them in
        completion order.  With ``FLAGS_serving_overlap`` the loop keeps
        one tick in flight: dispatch t+1 (chaining t's device last-token
        column), THEN harvest t — device compute and host harvest/
        detokenize overlap instead of strictly alternating."""
        from ..observability import http as _http
        _http.start_from_flags()   # no-op unless FLAGS_metrics_port > 0
        _http.attach_engine(self)
        _http.start_serving_from_flags()   # FLAGS_serving_http_port
        if self._warmup_info is None \
                and _flags.get_flag("serving_warmup"):
            self.warmup()          # compile the whole grid BEFORE
        self._mark_ready()         # traffic waits on a program build
        pend = None
        while True:
            if pend is None:
                if not (self.waiting or self.prefilling
                        or self._active_slots()):
                    break
                try:
                    pend = self._dispatch_tick(boundary=True)
                except Exception as e:  # noqa: BLE001 - crash-only guard
                    if not self._absorb_failure(e, ()):
                        raise
                    continue
                if pend is None:
                    continue     # waiting on evictions, as before
            nxt = None
            try:
                if self._can_overlap(pend):
                    nxt = self._dispatch_tick(boundary=False, chain=pend)
                    if nxt is not None:
                        nxt.overlapped = True
                        _M_OVERLAP.inc()
                        self._overlap_chunk_work(nxt)
                self._harvest_tick(pend)
            except Exception as e:  # noqa: BLE001 - crash-only guard
                if not self._absorb_failure(e, (pend, nxt)):
                    raise
                pend = None
                continue
            pend = nxt
        # final eviction sweep
        for slot in list(range(self.B)):
            if self.slot_req[slot] is not None and self.slot_req[slot].done:
                self._evict(slot)
        # drained-engine invariant: nothing leaked — every block is
        # free or held only by the prefix index (no-op when disarmed)
        _jaxsan.blocksan_verify(self)
        return self.finished

    def serve_forever(self, stop_event, idle_s: float = 0.002) -> None:
        """Drive the engine until ``stop_event`` (a threading.Event) is
        set, serving traffic submitted concurrently — the loop behind
        the streaming endpoint (``FLAGS_serving_http_port``): handler
        threads `add_request` and read each request's token stream;
        this loop ticks while work exists and naps otherwise.  Runs the
        SYNCHRONOUS step cycle: a latency-facing frontend wants
        admissions (and cancellations) at every boundary, not deferred
        behind an overlapped tick.

        Crash-only (ISSUE 15): every step runs under the tick guard —
        one request's failure never kills the loop — and SIGTERM (main
        thread only) or ``POST /drain`` flips `request_drain()`, which
        this loop turns into a graceful `drain()` and a clean return."""
        import signal as _signal
        from ..observability import http as _http
        _http.start_from_flags()
        _http.attach_engine(self)
        _http.start_serving_from_flags()
        old_handler = None
        try:
            old_handler = _signal.signal(
                _signal.SIGTERM,
                lambda signum, frame: self.request_drain())
        except ValueError:
            pass    # not the main thread: POST /drain still works
        try:
            if self._warmup_info is None \
                    and _flags.get_flag("serving_warmup"):
                self.warmup()
            self._mark_ready()
            while not stop_event.is_set():
                if self._drain_requested and not self._draining:
                    self.drain()
                    return
                if self.waiting or self.prefilling \
                        or self._active_slots():
                    self._guarded_step()
                else:
                    time.sleep(idle_s)
        finally:
            if old_handler is not None:
                try:
                    _signal.signal(_signal.SIGTERM, old_handler)
                except ValueError:
                    pass

    # -------------------------------------- graceful drain (ISSUE 15)
    def request_drain(self) -> None:
        """Ask the engine to drain at its next boundary.  A bare bool
        store — safe from signal handlers and the POST /drain handler
        threads.  Admission closes immediately (`add_request` rejects,
        /healthz answers 503 draining); the engine loop performs the
        actual drain."""
        self._drain_requested = True

    def drain(self, deadline_s: Optional[float] = None) -> dict:
        """Graceful drain: flip admission off, cancel the waiting queue
        (``outcome=drained`` — their SSE streams end in an error
        frame), keep ticking under the crash-only guard until every
        in-flight request finishes or ``deadline_s``
        (``FLAGS_serving_drain_timeout_s``) expires, evict stragglers
        ``outcome=drained``, blocksan-verify the emptied ledger, then
        export the prefix cache when ``FLAGS_serving_prefix_export_dir``
        is set.  Idempotent per engine; returns (and stashes for
        ``stats()``/``health()``) the drain report."""
        if self._drain_info is not None:
            return self._drain_info
        if deadline_s is None:
            deadline_s = float(_flags.get_flag("serving_drain_timeout_s"))
        self._drain_requested = True
        self._draining = True
        t0 = time.monotonic()
        self._flightrec().record_event(
            "drain_start", waiting=len(self.waiting),
            running=self.B - len(self.free_slots))
        # the waiting queue was never admitted: hand it back NOW with a
        # terminal reason the client can retry on (another replica owns
        # the retry — this engine is going away)
        cancelled = 0
        for r in list(self.waiting):
            self._terminal_trace(r, "drained")
            self.finished.append(r)
            r._stream_push(None)
            cancelled += 1
        self.waiting.clear()
        self._update_pressure()
        # finish in-flight work (chunked prefills included: their
        # prompts already consumed compute) up to the deadline
        deadline = t0 + max(float(deadline_s), 0.0)
        while (self.prefilling or self._active_slots()) \
                and time.monotonic() < deadline:
            self._guarded_step()
        # deadline stragglers: evict with outcome=drained (their
        # partial streams end in an SSE error frame, blocks released)
        evicted = 0
        for slot in list(range(self.B)):
            req = self.slot_req[slot]
            if req is None:
                continue
            if req._prefilling:
                self._abort_prefill(req, outcome="drained")
                evicted += 1
            elif req.done:
                self._evict(slot)
            else:
                self._terminal_trace(req, "drained")
                self._evict(slot)
                req._stream_push(None)
                evicted += 1
        # drain-complete invariant: the ledger must reconcile to
        # empty-running — every block free or held only by the prefix
        # index (no-op unless blocksan is armed)
        _jaxsan.blocksan_verify(self)
        export = None
        export_dir = self._export_dir
        if self.prefix is not None and export_dir:
            try:
                export = self.export_prefix_cache(export_dir)
            except Exception as e:  # noqa: BLE001 - drain must finish
                export = {"error": f"{type(e).__name__}: {e}"[:200]}
                self._flightrec().record_event(
                    "prefix_export_failed", error=export["error"])
        self._drain_info = {
            "drained_s": round(time.monotonic() - t0, 4),
            "deadline_s": float(deadline_s),
            "cancelled_waiting": cancelled,
            "evicted_running": evicted,
            "export": export}
        self._flightrec().record_event(
            "drain_complete", **{k: v for k, v in
                                 self._drain_info.items()
                                 if k != "export"})
        return self._drain_info

    # ---------------------------- prefix-cache persistence (ISSUE 15)
    def _prefix_fingerprint(self) -> dict:
        """What an export's KV contents are a pure function of (besides
        the prompt tokens): pool geometry + dtype + quant mode + the
        draft-pool layout.  Import refuses a mismatch (reason=mismatch)
        — loading another geometry's bytes would be silent garbage.
        Weight EQUALITY is deliberately not fingerprinted (documented:
        restarting with different weights under the same config is the
        operator's contract, exactly like the persistent compile
        cache)."""
        cfg = self.model.cfg
        fp = {"num_layers": int(cfg.num_layers), "nh": self.nh,
              "hd": self.hd, "block_size": self.bs,
              "vocab_size": int(cfg.vocab_size),
              "dtype": str(np.dtype(
                  np.asarray(self.pools[0][0]).dtype)),
              "quant": self.quant_mode,
              "draft": bool(self.spec_model)}
        if self.spec_model:
            dcfg = self.draft.cfg
            fp["draft_layers"] = int(dcfg.num_layers)
            fp["draft_nh"] = int(dcfg.num_heads)
            fp["draft_hd"] = int(dcfg.hidden_size // dcfg.num_heads)
        return fp

    def export_prefix_cache(self, root: str) -> dict:
        """Serialize the prefix-cache index + every referenced block's
        KV contents (ALL layer pools, draft pools included) as an
        atomic, integrity-checked version under ``root`` — the PR 5
        manifest machinery: ``step_<N>.tmp`` -> sha256 manifest ->
        re-hash -> rename -> ``COMPLETE`` sentinel — so a reader can
        NEVER observe a torn export.  The gather is one device->host
        pool copy + numpy slicing (no compiled gather programs: export
        runs post-warmup and must not add program signatures)."""
        from ..distributed.checkpoint import manager as _ckpt
        if self.prefix is None:
            raise ValueError("prefix cache is disabled on this engine")
        t0 = time.perf_counter()
        index = self.prefix.export_state()
        blocks = sorted({e["block"] for e in index["entries"]})
        ids = np.asarray(blocks, np.int64)
        arrays = {"block_ids": ids}
        for li, (kk, vv) in enumerate(self.pools):
            arrays[f"k{li}"] = np.asarray(kk)[:, ids]
            arrays[f"v{li}"] = np.asarray(vv)[:, ids]
        if self.dpools is not None:
            for li, (kk, vv) in enumerate(self.dpools):
                arrays[f"dk{li}"] = np.asarray(kk)[:, ids]
                arrays[f"dv{li}"] = np.asarray(vv)[:, ids]
        index["meta"] = self._prefix_fingerprint()
        step = max(_ckpt.all_steps(root), default=0) + 1

        def write(tmp):
            with _chaos.checked_open(
                    os.path.join(tmp, "prefix_index.json"), "w") as f:
                json.dump(index, f)
            with _chaos.checked_open(
                    os.path.join(tmp, "prefix_blocks.npz"), "wb") as f:
                np.savez(f, **arrays)
            return ["prefix_index.json", "prefix_blocks.npz"]

        path = _ckpt.commit_single_rank(root, step, write)
        nbytes = sum(a.nbytes for a in arrays.values())
        info = {"step": step, "path": path,
                "entries": len(index["entries"]),
                "blocks": len(blocks), "bytes": int(nbytes),
                "export_s": round(time.perf_counter() - t0, 4)}
        self._flightrec().record_event("prefix_export", **info)
        return info

    def release_exported_prefix(self) -> int:
        """Export-side half of a KV handoff (inference/fleet/handoff.py):
        drop every index-only prefix entry so the blocks just serialized
        by :meth:`export_prefix_cache` return to the free pool — the
        importing engine now owns that KV, adopted through its own
        ``_alloc_block`` refcounts.  Entries whose block a running
        request still references are kept (releasing them frees
        nothing).  Returns blocks freed; graft-lint R011 requires every
        export+import pairing to call this on the export side."""
        if self.prefix is None:
            return 0
        freed = self.prefix.evict(
            self.num_blocks, self._release_block,
            lambda b: int(self.block_rc[b]) == 1)
        _jaxsan.blocksan_verify(self)
        self._flightrec().record_event(
            "prefix_handoff_release", blocks=freed)
        return freed

    def _import_prefix_cache(self, root: str) -> None:
        """Construction-time warm restart: walk export versions newest
        first, skip anything that fails manifest validation or does not
        match this engine's fingerprint (counted on
        ``serving.prefix_import_skipped_corrupt`` + a flight event —
        NEVER loaded), and rebuild the index from the first valid one:
        every entry re-pins a freshly allocated block through
        ``_alloc_block`` (rc==1 ≡ one index reference; blocksan's
        ledger sees every draw) and the exported KV bytes are installed
        into the zero-initialized pools with plain numpy + one
        device_put per pool array."""
        from ..distributed.checkpoint import manager as _ckpt
        skipped = 0
        for step in reversed(_ckpt.all_steps(root)):
            path = os.path.join(root, _ckpt.step_dir(step))
            reason = _ckpt.verify_version(path)
            if reason is not None:
                skipped += 1
                _M_PREFIX_IMPORT_SKIP.inc(reason="corrupt")
                self._flightrec().record_event(
                    "prefix_import_skip", step=step, reason=reason)
                continue
            try:
                with open(os.path.join(path, "prefix_index.json")) as f:
                    index = json.load(f)
                if index.get("meta") != self._prefix_fingerprint():
                    skipped += 1
                    _M_PREFIX_IMPORT_SKIP.inc(reason="mismatch")
                    self._flightrec().record_event(
                        "prefix_import_skip", step=step,
                        reason="engine fingerprint mismatch")
                    continue
                n = self._install_prefix_export(path, index)
            except Exception as e:  # noqa: BLE001 - restart must not die
                skipped += 1
                _M_PREFIX_IMPORT_SKIP.inc(reason="unreadable")
                self._flightrec().record_event(
                    "prefix_import_skip", step=step,
                    reason=f"{type(e).__name__}: {e}"[:200])
                continue
            self._prefix_import_info = {
                "step": step, "blocks": n, "skipped_corrupt": skipped}
            if n:
                _M_PREFIX_IMPORT.inc(n)
            self._flightrec().record_event(
                "prefix_import", step=step, blocks=n, skipped=skipped)
            # checksum the imported (registered-immutable) blocks as
            # ground truth — no-op unless blocksan is armed
            _jaxsan.blocksan_snapshot(self)
            return
        if skipped:
            self._prefix_import_info = {
                "step": None, "blocks": 0, "skipped_corrupt": skipped}

    def _install_prefix_export(self, path: str, index: dict) -> int:
        """Rebuild index entries + pool contents from one validated
        export version.  Returns blocks imported."""
        data = np.load(os.path.join(path, "prefix_blocks.npz"),
                       allow_pickle=False)
        old_ids = [int(b) for b in data["block_ids"]]
        pos = {b: i for i, b in enumerate(old_ids)}
        mapping: dict = {}

        def alloc():
            if not self.free_blocks:
                return None
            return self._alloc_block()

        def assign(old, new):
            mapping[old] = new

        n = self.prefix.import_state(index, alloc, assign)
        if not mapping:
            return 0

        def install(pools, prefix, sharded):
            out = []
            for li, (kk, vv) in enumerate(pools):
                hk = np.zeros(kk.shape, np.asarray(kk).dtype)
                hv = np.zeros(vv.shape, hk.dtype)
                src_k = data[f"{prefix}k{li}"]
                src_v = data[f"{prefix}v{li}"]
                for old, new in mapping.items():
                    hk[:, new] = src_k[:, pos[old]]
                    hv[:, new] = src_v[:, pos[old]]
                jk, jv = jnp.asarray(hk), jnp.asarray(hv)
                if self._tp_mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec
                    from . import tp as _tp
                    spec = _tp.pool_spec() if sharded else PartitionSpec()
                    jk = jax.device_put(
                        jk, NamedSharding(self._tp_mesh, spec))
                    jv = jax.device_put(
                        jv, NamedSharding(self._tp_mesh, spec))
                out.append((jk, jv))
            return out

        self.pools = install(self.pools, "", sharded=True)
        if self.dpools is not None:
            self.dpools = install(self.dpools, "d", sharded=False)
        return n

    def _mark_ready(self) -> None:
        """Admission is open and (when configured) warmup has run: the
        /healthz readiness probe flips from 503 warmup to 200."""
        if not self._ready:
            self._ready = True
            self._t_serve_start = time.monotonic()

    @property
    def ready(self) -> bool:
        return self._ready

    def health(self) -> dict:
        """The /healthz readiness document (observability/http.py): 503
        `{"ready": false, "reason": "warmup"}` until run()/
        serve_forever() completed warmup and opened admission, 503
        `{"ready": false, "reason": "draining"}` (with live
        in-flight/waiting counts) once a drain was requested, then the
        warmup / queue-depth / uptime evidence.  Reads only host-side
        scheduler ints — safe from the endpoint's handler threads."""
        if not self._ready:
            return {"ready": False, "reason": "warmup"}
        if self._draining or self._drain_requested:
            running = self.B - len(self.free_slots)
            doc = {"ready": False, "reason": "draining",
                   "in_flight": running, "waiting": len(self.waiting),
                   "prefilling": len(self.prefilling)}
            if self._drain_info is not None:
                doc["drained"] = True
                doc["drained_s"] = self._drain_info["drained_s"]
            return doc
        running = self.B - len(self.free_slots)
        doc = {"ready": True, "running": running,
               "waiting": len(self.waiting),
               "queue_depth": running + len(self.waiting),
               "slots": self.B,
               "free_slots": len(self.free_slots),
               "prefilling": len(self.prefilling),
               "uptime_s": round(
                   time.monotonic() - self._t_serve_start, 3)}
        # queue-position TTFT evidence for the fleet router's shed
        # predictor (inference/fleet/router.py): recent admission rate
        # plus median observed TTFT.  Always-on host floats, not the
        # metrics-gated sketches.
        doc["ttft_evidence"] = self._ttft_evidence()
        if self._warmup_info is not None:
            doc["warmup"] = {k: self._warmup_info[k] for k in
                             ("warmup_s", "programs", "aot_programs")}
        return doc

    def _ttft_evidence(self) -> dict:
        """Admission-rate + recent-TTFT summary for /healthz: the two
        numbers a queue-position model needs to predict the TTFT a
        request would see if routed here now."""
        ev = {"admit_rate_per_s": 0.0, "ttft_p50_s": 0.0,
              "samples": len(self._ttft_recent)}
        times = list(self._admit_times)
        if len(times) >= 2:
            span = times[-1] - times[0]
            if span > 0:
                ev["admit_rate_per_s"] = round((len(times) - 1) / span, 4)
        if self._ttft_recent:
            srt = sorted(self._ttft_recent)
            ev["ttft_p50_s"] = round(srt[len(srt) // 2], 6)
        # live decode-capacity evidence (ISSUE 17): median tick-level
        # TPOT + mean finished length let the router cap a stale
        # admission rate by what the decode loop can actually drain
        if self._ev_tpot.count > 0:
            ev["tpot_p50_s"] = round(self._ev_tpot.quantile(0.5), 6)
        if self._ev_finished > 0:
            ev["avg_tokens_out"] = round(
                self._ev_finished_tokens / self._ev_finished, 3)
        return ev

    def telemetry_snapshot(self) -> dict:
        """Always-on engine evidence for the fleet federation poll
        (``/metrics/snapshot``): terminal-outcome tallies, the TTFT-SLO
        violation count, and the tick-level TPOT sketch state.  Host
        floats/ints only — independent of FLAGS_enable_metrics."""
        return {"outcomes": dict(self._ev_outcomes),
                "slo_violations_ttft": self._ev_slo_viol,
                "finished": self._ev_finished,
                "finished_tokens": self._ev_finished_tokens,
                "tpot_sketch": self._ev_tpot.to_state(),
                "ttft_evidence": self._ttft_evidence()}

    def stats(self) -> dict:
        running = self.B - len(self.free_slots)
        # blocks held ONLY by the prefix index are free capacity: the
        # allocator reclaims them on demand (index eviction), so the
        # "nothing leaked" invariant free_blocks == num_blocks holds
        # after a drained engine even with resident prefixes
        reclaimable = self.prefix.reclaimable(self.block_rc) \
            if self.prefix is not None else 0
        out = {"steps": self.steps, "ticks": self.ticks,
               "tokens_out": self.tokens_out,
               "free_blocks": len(self.free_blocks) + reclaimable,
               "reserved": self.reserved,
               "active": len(self._active_slots()),
               "running": running,
               "waiting": len(self.waiting),
               "queue_depth": running + len(self.waiting),
               "pad_buckets": list(self.pad_ladder),
               "tp_degree": self.tp,
               "prefill_chunk": self.chunk,
               "prefilling": len(self.prefilling),
               "prefill_chunks": self.prefill_chunks_total,
               "slo_sheds": self.slo_sheds,
               "tick_errors": self.tick_errors,
               "poisoned_requests": self.poisoned_requests,
               "dispatch_retries": self.dispatch_retries,
               "draining": bool(self._draining or self._drain_requested)}
        if self._drain_info is not None:
            out["drain"] = dict(self._drain_info)
        if self.spec:
            per_slot = {
                slot: round(r._spec_accepted / r._spec_proposed, 4)
                for slot, r in enumerate(self.slot_req)
                if r is not None and r._spec_proposed}
            out["speculative"] = {
                "spec_k": self.spec_k,
                "k_now": self.spec_k_now,
                "ladder": list(self.spec_ladder),
                "adaptive": self.spec_adaptive,
                "k_switches": self.spec_k_switches,
                "draft": self.spec_kind,
                "ticks": self.spec_ticks,
                "proposed_tokens": self.spec_proposed,
                "accepted_tokens": self.spec_accepted,
                "accept_rate": round(
                    self.spec_accepted / max(self.spec_proposed, 1), 4),
                "accept_ewma": (None if self._accept_ewma is None
                                else round(self._accept_ewma, 4)),
                "ineligible_slots": self.spec_ineligible_slots,
                "per_slot_accept_rate": per_slot}
        if self._quant_stats is not None:
            out["quant"] = dict(self._quant_stats)
        if self.prefix is not None:
            out["prefix_cache"] = {
                "entries": len(self.prefix),
                "hits": self.prefix.hits,
                "misses": self.prefix.misses,
                "blocks_shared": self.prefix.blocks_shared,
                "evictions": self.prefix.evictions,
                "reclaimable_blocks": reclaimable}
            if self._prefix_import_info is not None:
                out["prefix_cache"]["import"] = \
                    dict(self._prefix_import_info)
        if self._warmup_info is not None:
            out["warmup"] = {k: self._warmup_info[k] for k in
                             ("warmup_s", "programs", "aot_programs")}
        # the engine X-ray ledger (ISSUE 14) — process-wide like the
        # compile tracker and the latency sketches below
        xr = _xray.report(top=16)
        out["xray"] = {"sample_interval": xr["sample_interval"],
                       "programs_tracked": xr["programs_tracked"],
                       "total_est_device_s": xr["total_est_device_s"],
                       "programs": xr["programs"]}
        # p50/p90/p99 straight off the streaming sketches — process-wide
        # (the sketches aggregate every engine in the process, like the
        # /metrics scrape they feed)
        lat = {}
        for key, sk in (("ttft", _M_TTFT), ("tpot", _M_TPOT),
                        ("e2e", _M_E2E), ("queue_wait", _M_QWAIT)):
            if not sk.count():
                continue
            lat[key] = {f"p{round(q * 100)}": round(sk.quantile(q), 6)
                        for q in (0.5, 0.9, 0.99)}
        if lat:
            out["latency"] = lat
        return out
