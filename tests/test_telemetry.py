"""Training telemetry (ISSUE 2): the shared FLOPs/MFU helper, the
StepTimeline's per-step records and fractions, the flight recorder's
ring + dumps, the NaN/Inf watchdog (including its verified no-op path),
the profiler chrome-export round trip for spans, and the dump CLI."""

import json
import math
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.observability import (flight_recorder as fr, flops,
                                      metrics, telemetry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    metrics.reset()
    fr.default_recorder().clear()
    telemetry.default_timeline().reset()
    yield
    paddle.set_flags({"enable_metrics": True, "enable_nan_watchdog": False,
                      "flight_dump_dir": "", "nan_watchdog_interval": 1})
    metrics.reset()
    fr.default_recorder().clear()
    telemetry.default_timeline().reset()


# ------------------------------------------------------------ FLOPs helper

def test_flops_helper_is_the_single_source():
    """The models' flops_per_token must equal the shared helper exactly —
    deduplicating the estimators is how the 40.7%-vs-58% MFU dispute
    becomes impossible to repeat."""
    from paddle_tpu.models.bert import BertForMaskedLM, bert_tiny
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
    gpt = GPTForCausalLM(gpt3_tiny())
    assert gpt.flops_per_token(128) == flops.training_flops_per_token(
        gpt.num_params(), gpt.cfg.num_layers, gpt.cfg.hidden_size, 128)
    bert = BertForMaskedLM(bert_tiny())
    assert bert.flops_per_token(64) == flops.training_flops_per_token(
        bert.num_params(), bert.cfg.num_layers, bert.cfg.hidden_size, 64)
    # 6N floor without the attention shape
    assert flops.training_flops_per_token(100) == 600.0


def test_cost_model_uses_shared_flops():
    from paddle_tpu.distributed.auto_tuner.cost_model import (
        Hardware, ModelSpec, estimate_params, estimate_step_time)
    from paddle_tpu.distributed.auto_tuner.tuner import Trial
    spec = ModelSpec(num_layers=4, hidden_size=64, num_heads=4,
                     vocab_size=128, seq_len=32, global_batch_size=8)
    trial = Trial(dp=1, mp=1, pp=1, sharding=1, micro_batch_size=8)
    hw = Hardware(peak_flops=1e12, mfu_ceiling=1.0)
    fpt = flops.training_flops_per_token(
        estimate_params(spec), spec.num_layers, spec.hidden_size,
        spec.seq_len)
    tokens = spec.global_batch_size * spec.seq_len
    assert estimate_step_time(trial, spec, hw) == pytest.approx(
        fpt * tokens / 1e12)


def test_peak_flops_table():
    assert flops.peak_flops("TPU v5 lite") == 197e12
    assert flops.peak_flops("TPU v4") == 275e12
    assert flops.peak_flops("cpu") == 2e12
    assert flops.mfu(1000.0, 1e9, peak=2e12) == pytest.approx(0.5)
    assert flops.mfu(1000.0, 1e9, device_kind="cpu") == pytest.approx(0.5)


# ------------------------------------------------------------- StepTimeline

def test_step_timeline_records_fractions_and_mfu():
    tl = telemetry.StepTimeline(name="t", flops_per_token=1e6,
                                peak_flops=1e12, ici_bandwidth=1e9)
    comm = metrics.counter("collective.bytes")
    for i in range(3):
        with tl.step(tokens=500) as st:
            time.sleep(0.004)
            if i == 2:
                comm.inc(2_000_000, op="all_reduce")  # 2e6 B / 1e9 B/s = 2ms
        st.annotate(loss=0.5 + i)
    recs = tl.records
    assert [r["step"] for r in recs] == [0, 1, 2]
    for r in recs:
        # fractions are rounded to 4 decimals -> sum within rounding
        assert abs(sum(r["fractions"].values()) - 1.0) < 2e-4
        assert r["tokens"] == 500 and r["wall_s"] > 0
        assert r["mfu"] == pytest.approx(
            r["tokens_per_sec"] * 1e6 / 1e12, rel=1e-3)
    assert recs[2]["comm_bytes"] == 2_000_000
    assert recs[2]["comm_s_est"] > 0
    assert recs[2]["fractions"]["comm"] > recs[0]["fractions"]["comm"]
    assert recs[-1]["loss"] == 2.5
    summ = tl.summary()
    assert summ["schema"] == telemetry.TELEMETRY_SCHEMA
    assert summ["steps"] == 3 and summ["loss_last"] == 2.5
    assert set(summ["fractions"]) == {"compute", "comm", "host"}
    assert summ["mfu"] > 0 and summ["flops_per_token"] == 1e6
    # records also landed in the flight ring
    assert len(fr.default_recorder().steps()) == 3


def test_step_timeline_separates_compile_from_steady():
    """A step that pays a jit compile is charged host time and excluded
    from the steady-state tokens/sec."""
    tl = telemetry.StepTimeline(name="c")
    comp = metrics.histogram("jit.compile_seconds")
    with tl.step(tokens=10):
        comp.observe(5.0, fn="f", stage="compile")  # simulated compile
    with tl.step(tokens=10):
        time.sleep(0.002)
    assert tl.records[0]["compile_s"] == pytest.approx(5.0)
    summ = tl.summary()
    assert summ["steps"] == 2 and summ["steady_steps"] == 1


def test_step_timeline_noop_when_metrics_disabled():
    tl = telemetry.StepTimeline(name="off")
    paddle.set_flags({"enable_metrics": False})
    with tl.step(tokens=5) as st:
        st.tokens = 7          # tolerated, ignored
    st.annotate(loss=1.0)
    assert tl.records == []
    assert fr.default_recorder().steps() == []
    # empty summary is schema-stable (no KeyError for consumers)
    summ = tl.summary()
    assert summ["steps"] == 0 and summ["tokens_per_sec"] == 0.0
    assert set(summ["fractions"]) == {"compute", "comm", "host"}
    paddle.set_flags({"enable_metrics": True})
    with tl.step(tokens=5):
        pass
    assert len(tl.records) == 1


def test_step_annotate_custom_keys_inside_bracket():
    """Custom annotations made inside the bracket must land in the
    sealed record just like post-seal ones."""
    tl = telemetry.StepTimeline(name="ann")
    with tl.step(tokens=1) as st:
        st.annotate(grad_norm=2.5, loss=0.1)
    st.annotate(lr=0.01)
    rec = tl.records[0]
    assert rec["grad_norm"] == 2.5 and rec["loss"] == 0.1
    assert rec["lr"] == 0.01


# ---------------------------------------------------------- flight recorder

def test_flight_recorder_ring_is_bounded_and_dumps(tmp_path):
    rec = fr.FlightRecorder(capacity=4)
    for i in range(10):
        rec.record_step({"step": i})
    rec.record_event("marker", detail="x")
    assert [r["step"] for r in rec.steps()] == [6, 7, 8, 9]
    path = tmp_path / "dump.json"
    doc = rec.dump(str(path), reason="unit")
    on_disk = json.loads(path.read_text())
    assert on_disk["schema"] == fr.FLIGHT_SCHEMA
    assert on_disk["reason"] == "unit"
    assert [r["step"] for r in on_disk["steps"]] == [6, 7, 8, 9]
    assert on_disk["events"][0]["kind"] == "marker"
    assert doc["first_nonfinite"] is None
    assert "metrics" in on_disk


def test_flight_ring_resizes_via_flag():
    rec = fr.default_recorder()
    for i in range(10):
        rec.record_step({"step": i})
    paddle.set_flags({"flight_recorder_steps": 3})
    try:
        assert [r["step"] for r in rec.steps()] == [7, 8, 9]
        rec.record_step({"step": 10})
        assert [r["step"] for r in rec.steps()] == [8, 9, 10]
    finally:
        paddle.set_flags({"flight_recorder_steps": 64})
    assert rec.capacity == 64


def test_batch_tokens_heuristic():
    from paddle_tpu.hapi.model import _batch_tokens
    ids = np.zeros((4, 16), np.int32)          # [B, S] token ids
    imgs = np.zeros((8, 3, 28, 28), np.float32)
    feats = np.zeros((5, 7), np.float32)       # 2-D but float: rows
    assert _batch_tokens([ids]) == 64
    assert _batch_tokens([imgs]) == 8
    assert _batch_tokens([feats]) == 5
    assert _batch_tokens([]) == 0


def test_check_finite_is_noop_when_flag_off():
    """Verified no-op path: with the watchdog flag off the probe must not
    touch the value at all (no host sync on device arrays)."""

    class Untouchable:
        def __float__(self):
            raise AssertionError("watchdog touched the value while off")

    assert fr.enabled() is False
    assert fr.check_finite(Untouchable(), site="off") is True
    assert fr.default_recorder().first_nonfinite is None


def test_check_finite_trips_and_dumps(tmp_path):
    paddle.set_flags({"enable_nan_watchdog": True,
                      "flight_dump_dir": str(tmp_path)})
    rec = fr.default_recorder()
    rec.record_step({"step": 41, "loss": 1.0})
    assert fr.check_finite(3.0, site="fine", step=41) is True
    assert fr.check_finite(float("inf"), site="train.loss", step=42) is False
    assert rec.first_nonfinite["site"] == "train.loss"
    assert rec.first_nonfinite["step"] == 42
    dump = fr.last_dump_path()
    assert dump and os.path.dirname(dump) == str(tmp_path)
    doc = json.loads(open(dump).read())
    assert doc["first_nonfinite"]["step"] == 42
    assert {"step": 41, "loss": 1.0} in doc["steps"]
    # later trips don't overwrite the FIRST offending site
    fr.check_finite(float("nan"), site="other", step=99)
    assert rec.first_nonfinite["site"] == "train.loss"


def test_nan_watchdog_hapi_fit_dumps_offending_step(tmp_path):
    """ISSUE 2 acceptance: inject a non-finite loss into a tiny hapi fit
    and assert an automatic dump naming the offending step, with the
    last-K step records around it."""
    from paddle_tpu.hapi import Model

    class Blobs(paddle.io.Dataset):
        def __init__(self, n=12):
            rng = np.random.RandomState(0)
            self.x = rng.rand(n, 4).astype(np.float32)
            self.y = (rng.rand(n) * 2).astype(np.int64)

        def __len__(self):
            return len(self.y)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    paddle.set_flags({"enable_nan_watchdog": True,
                      "flight_dump_dir": str(tmp_path)})
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    ce = nn.CrossEntropyLoss()
    calls = {"n": 0}

    def poisoned_loss(out, label):
        calls["n"] += 1
        factor = float("nan") if calls["n"] >= 2 else 1.0
        return ce(out, label) * factor

    m = Model(net)
    # eager mode so the Python-side injection fires per step (a captured
    # program would bake the first factor in)
    m.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                      parameters=net.parameters()),
              loss=poisoned_loss, jit_compile=False)
    m.fit(Blobs(), batch_size=4, epochs=1, verbose=0)

    rec = fr.default_recorder()
    assert rec.first_nonfinite is not None
    assert rec.first_nonfinite["site"].endswith(".loss")
    bad_step = rec.first_nonfinite["step"]
    dump = fr.last_dump_path()
    assert dump and os.path.dirname(dump) == str(tmp_path)
    doc = json.loads(open(dump).read())
    assert doc["first_nonfinite"]["step"] == bad_step
    by_step = {r["step"]: r for r in doc["steps"]
               if r.get("timeline") == "train"}
    # the offending step's record is in the ring with a non-finite loss,
    # preceded by a finite one
    assert bad_step in by_step
    assert not math.isfinite(by_step[bad_step]["loss"])
    assert any(r["loss"] is not None and math.isfinite(r["loss"])
               for s, r in by_step.items() if s < bad_step)
    # hapi brackets include the loss host read -> records are synced
    # (wall_s is completed-step time, not enqueue time)
    assert all(r["synced"] for r in by_step.values())


def test_watchdog_fires_with_metrics_disabled(tmp_path):
    """The watchdog must stay armed when the metrics registry is off —
    the two flags are independent gates (telemetry records are skipped,
    the finite probe is not)."""
    from paddle_tpu.hapi import Model
    paddle.set_flags({"enable_metrics": False, "enable_nan_watchdog": True,
                      "flight_dump_dir": str(tmp_path)})

    def nan_loss(out, label):
        return nn.CrossEntropyLoss()(out, label) * float("nan")

    net = nn.Sequential(nn.Linear(4, 2))
    m = Model(net)
    m.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                      parameters=net.parameters()),
              loss=nan_loss, jit_compile=False)
    m.train_batch([np.ones((4, 4), np.float32)], [np.zeros((4,), np.int64)])
    rec = fr.default_recorder()
    assert rec.first_nonfinite is not None
    assert rec.first_nonfinite["site"] == "hapi.train.loss"
    assert fr.last_dump_path() and \
        os.path.dirname(fr.last_dump_path()) == str(tmp_path)


def test_exception_in_train_step_dumps(tmp_path):
    from paddle_tpu.hapi import Model
    paddle.set_flags({"enable_nan_watchdog": True,
                      "flight_dump_dir": str(tmp_path)})

    def exploding_loss(out, label):
        raise RuntimeError("injected backend death")

    net = nn.Sequential(nn.Linear(4, 2))
    m = Model(net)
    m.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                      parameters=net.parameters()),
              loss=exploding_loss, jit_compile=False)
    x = np.ones((4, 4), np.float32)
    y = np.zeros((4,), np.int64)
    with pytest.raises(RuntimeError, match="injected backend death"):
        m.train_batch([x], [y])
    dump = fr.last_dump_path()
    assert dump and os.path.dirname(dump) == str(tmp_path)
    doc = json.loads(open(dump).read())
    assert doc["reason"].startswith("exception")
    assert any(e["kind"] == "exception" and "injected backend death"
               in e["error"] for e in doc["events"])


@pytest.mark.slow   # tier-1 budget (ISSUE 9): heavy, not on the serving/training core path
def test_hybrid_step_feeds_timeline_and_watchdog(tmp_path):
    """The fleet hybrid step records telemetry and its periodic loss
    probe trips on a poisoned parameter tree."""
    import jax
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.hybrid_step import (
        HybridConfig, init_gpt_params, init_zero_state, hybrid_param_specs,
        make_hybrid_train_step, stack_for_pipeline)
    if len(jax.devices()) < 1:
        pytest.skip("no devices")
    cfg = HybridConfig(pp=1, mp=1, dp=1, n_microbatches=1, vocab_size=64,
                       hidden_size=32, num_layers=2, num_heads=2,
                       seq_len=16, sequence_parallel=False)
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                ("pp", "dp", "mp"))
    params = stack_for_pipeline(init_gpt_params(jax.random.key(0), cfg), cfg)
    specs = hybrid_param_specs(cfg)
    m, v, _ = init_zero_state(params, specs, mesh)
    step = make_hybrid_train_step(mesh, cfg)
    ids = np.zeros((1, 2, 16), np.int32)
    paddle.set_flags({"enable_nan_watchdog": True,
                      "flight_dump_dir": str(tmp_path)})
    loss, params, m, v = step(params, m, v, 1.0, ids)
    assert np.isfinite(float(np.asarray(loss)))
    recs = [r for r in fr.default_recorder().steps()
            if r.get("mode") == "hybrid"]
    assert recs and recs[-1]["tokens"] == ids.size
    # poison the weights -> next step's loss is non-finite -> watchdog
    params["wte"] = params["wte"] * float("nan")
    step(params, m, v, 2.0, ids)
    assert fr.default_recorder().first_nonfinite is not None
    assert fr.default_recorder().first_nonfinite["site"] == \
        "hybrid.train_step.loss"


def test_serving_tick_flight_records_and_deferral_reason():
    from paddle_tpu.inference.serving import Request, ServingEngine
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
    paddle.seed(0)
    model = GPTForCausalLM(gpt3_tiny())
    model.eval()
    # pool sized so the second request must wait for the first to finish
    eng = ServingEngine(model, max_batch=2, max_context=64, block_size=16,
                        num_blocks=4)
    rng = np.random.RandomState(0)
    eng.add_request(Request(rng.randint(1, 100, (16,)), max_new_tokens=30))
    eng.add_request(Request(rng.randint(1, 100, (16,)), max_new_tokens=30))
    eng.run()
    ticks = [r for r in fr.default_recorder().steps()
             if r.get("timeline") == "serving"]
    assert ticks, "serving ticks must land in the flight ring"
    assert all("tokens" in t and "wall_s" in t for t in ticks)
    rej = metrics.get("serving.rejections")
    assert rej.value(reason="pool_exhausted") == 1  # once, not per tick


@pytest.mark.slow   # tier-1 budget (ISSUE 9): heavy, not on the serving/training core path
def test_bench_rung_failure_writes_flight_dump(tmp_path):
    """Satellite: a dying rung leaves a flight-recorder dump next to the
    JSON record, so an rc!=0-style artifact still carries evidence."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_flight_test", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    from paddle_tpu.observability import harness

    @harness.register_rung("_t_dying", smoke=True)
    def dying(ctx):
        fr.default_recorder().record_step({"step": 1, "note": "pre-death"})
        raise ValueError("synthetic rung death")

    try:
        art = tmp_path / "art.json"
        rc = bench.main(["--rungs", "_t_dying", "--out", str(art)])
    finally:
        harness._REGISTRY.pop("_t_dying", None)
    assert rc == 0
    doc = json.loads(art.read_text())
    rec = {r["rung"]: r for r in doc["records"]}["_t_dying"]
    assert rec["ok"] is False and "synthetic rung death" in rec["error"]
    dump_path = rec["flight_dump"]
    assert os.path.dirname(dump_path) == str(tmp_path)
    dump = json.loads(open(dump_path).read())
    assert dump["schema"] == fr.FLIGHT_SCHEMA
    assert dump["reason"] == "rung_failure:_t_dying"
    assert {"step": 1, "note": "pre-death"} in dump["steps"]
    assert any(e["kind"] == "rung_error" and "synthetic rung death"
               in e["error"] for e in dump["events"])


# --------------------------------------------------- profiler round trip

def test_profiler_chrome_export_roundtrip_with_spans(tmp_path):
    """Satellite: observability.span events must land in the exported
    chrome trace with usable timestamps."""
    from paddle_tpu import observability as obs
    from paddle_tpu.profiler import Profiler
    with Profiler() as p:
        with obs.span("telemetry_region"):
            with obs.span("inner_region"):
                time.sleep(0.002)
        path = p.export(str(tmp_path / "trace.json"))
    events = json.loads(open(path).read())["traceEvents"]
    spans = {e["name"]: e for e in events if e["cat"] == "span"}
    assert {"telemetry_region", "inner_region"} <= set(spans)
    for e in spans.values():
        assert e["ph"] == "X" and e["dur"] > 0 and e["ts"] >= 0
    # nesting preserved on the timeline
    outer, inner = spans["telemetry_region"], spans["inner_region"]
    assert outer["ts"] <= inner["ts"]
    assert outer["ts"] + outer["dur"] >= inner["ts"] + inner["dur"]
    # the profiler's record start/stop transitions land in the flight
    # ring, so crash dumps say whether a trace was live
    states = [e["state"] for e in fr.default_recorder().events()
              if e["kind"] == "profiler"]
    assert "record_start" in states and "record_stop" in states


# ------------------------------------------------------------------ CLI

@pytest.mark.slow   # tier-1 budget (R010): three CLI children, ~4s
def test_dump_cli_subprocess(tmp_path):
    """Fast-tier smoke of `python -m paddle_tpu.observability.dump`
    (mirrors the bench --smoke subprocess pattern)."""
    rec = fr.FlightRecorder(capacity=2)
    rec.record_step({"step": 7, "loss": 0.5})
    rec.dump(str(tmp_path / "flight_manual_1.json"), reason="cli-test")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.observability.dump",
         "--dir", str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout)
    assert doc["schema"] == fr.FLIGHT_SCHEMA
    assert doc["reason"] == "cli-test"
    assert doc["steps"] == [{"step": 7, "loss": 0.5}]
    # --registry mode prints a metrics snapshot document
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.observability.dump",
         "--registry"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert json.loads(out.stdout)["schema"] == "paddle_tpu.metrics/v1"
    # empty dir -> exit 1, stdout stays clean
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.observability.dump",
         "--dir", str(tmp_path / "empty")],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert out.returncode == 1 and not out.stdout.strip()
