"""Ring attention: exact long-context attention over a sequence-parallel
mesh axis.

Parity target: the reference's long-context path is flash-attention +
sequence/context parallel groups (`fleet/utils/sequence_parallel_utils.py`,
`phi/kernels/gpu/flash_attn_kernel.cu` with cu_seqlens); this module is the
TPU-native equivalent SURVEY §5.7 calls out as "where TPU should beat the
reference": each device holds S/n of the sequence, K/V blocks rotate around
the ring via `ppermute` over ICI while every hop's partial attention is
accumulated with the flash-attention online-softmax update — compute and
communication overlap, no device ever materialises the full K/V.

The hop compute runs INSIDE the Pallas flash kernels (`ops/pallas_flash.py`):
each hop is a blockwise-VMEM flash forward over this rank's queries and the
K/V chunk currently resident, emitting a normalized partial output plus its
logsumexp rows; hops merge at the jnp level with the standard two-softmax
combine on [B, S_local, H, D]-shaped carries only — the [S_q, S_k]
probability block never exists outside VMEM.  The backward re-rotates K/V
around the ring with traveling f32 dk/dv accumulators and re-derives each
hop's block gradients with the Pallas FlashAttention-2 backward kernels
against the *global* logsumexp (the FA2 identities hold chunkwise under the
global normalizer), so the memory high-water line per member is the f32
accumulators — not stacked per-hop residuals.

Layout: public API is (batch, num_heads, seq, head_dim); the Pallas kernels
run in paddle's flash layout [B, S, nh, hd] internally.

Shapes outside the kernels' support envelope (head_dim not in {64,128,256},
ragged chunk alignment, custom scale) fall back to an exact jnp online-
softmax path (`_block_update`).

Use inside `shard_map` (axis_name = the sequence/context-parallel mesh
axis), or call `ring_attention` with a mesh for the wrapped version.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ....core.jax_compat import axis_size as _axis_size, \
    pvary as _compat_pvary, shard_map as _compat_shard_map
from ....ops import pallas_flash

__all__ = ["ring_attention_local", "ring_attention",
           "ring_attention_chunked", "ulysses_attention_local",
           "ulysses_attention"]

_NEG = -1e30

# hop kinds (lax.switch indices): this rank's queries vs the resident chunk
_SKIP, _FULL, _DIAG = 0, 1, 2


def _register():
    from ....ops.registry import register_op
    register_op("ring_attention", _ring_attention_val)
    register_op("ulysses_attention", _ulysses_attention_val)


def _check_gqa(nh: int, nkv: int) -> None:
    if nkv == 0 or nh % nkv:
        raise ValueError(
            f"GQA: num_heads ({nh}) must be a multiple of kv heads "
            f"({nkv})")


def _expand_kv_heads(q, k, v):
    """GQA support for the jnp/dense fallback paths: the Pallas kernels
    broadcast nkv < nh natively, but the fallbacks' 'bhqd,bhkd' einsums
    need matching head axes — repeat each kv head nh/nkv times (BHSD
    layout, head axis 1).  ADVICE r5 #3: without this, GQA inputs outside
    the kernel envelope crashed on einsum shapes instead of computing."""
    nh, nkv = q.shape[1], k.shape[1]
    if nkv == nh:
        return k, v
    _check_gqa(nh, nkv)
    r = nh // nkv
    return jnp.repeat(k, r, axis=1), jnp.repeat(v, r, axis=1)


def _block_update(q, k, v, acc, m, l, q_off, k_off, causal, scale):
    """One flash-attention online-softmax step on a (S_q, S_k) block.

    jnp fallback for shapes the Pallas kernels don't cover."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_off + jax.lax.iota(jnp.int32, q.shape[2])[:, None]
        kpos = k_off + jax.lax.iota(jnp.int32, k.shape[2])[None, :]
        s = jnp.where(qpos >= kpos, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))              # (B, H, Sq)
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])                   # (B, H, Sq, Sk)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(p.dtype),
                    preferred_element_type=jnp.float32)
    acc_new = acc * alpha[..., None] + pv
    return acc_new, m_new, l_new


# --------------------------------------------------------------------------
# Pallas-backed hop machinery (shared by the multi-device ring and the
# single-device chunked member)
# --------------------------------------------------------------------------

def _bhsd_to_bshd(x):
    return jnp.transpose(x, (0, 2, 1, 3))


_bshd_to_bhsd = _bhsd_to_bshd  # the permutation is its own inverse


def _pallas_ok(q_bshd_shape, k_bshd_shape):
    """Whether the Pallas hop path covers these per-hop shapes (a custom
    scale never affects path selection — callers fold it into q)."""
    return pallas_flash.supported(q_bshd_shape, k_bshd_shape)


def _hop_fwd(q, kc, vc, hop_idx, interpret):
    """One ring hop, computed by the Pallas flash forward.

    q [B, Sq, nh, hd]; kc/vc [B, C, nkv, hd] (the resident chunk).
    hop_idx: _SKIP | _FULL | _DIAG (traced).  Returns the hop's normalized
    partial output (f32, [B, Sq, nh, hd]) and logsumexp rows
    (f32, [B, nh, Sq]); a skipped hop contributes lse = -1e30."""
    B, Sq, nh, hd = q.shape

    def skip(q, kc, vc):
        return (jnp.zeros((B, Sq, nh, hd), jnp.float32),
                jnp.full((B, nh, Sq), _NEG, jnp.float32))

    def mk(causal):
        def run(q, kc, vc):
            o, lse = pallas_flash.flash_attention_fwd(
                q, kc, vc, causal=causal, interpret=interpret)
            return o.astype(jnp.float32), lse[..., 0]
        return run

    return jax.lax.switch(hop_idx, (skip, mk(False), mk(True)), q, kc, vc)


def _merge(out_a, lse_a, out_b, lse_b):
    """Two-softmax combine: outs are normalized partials [B, S, nh, hd] f32,
    lses [B, nh, S].  Safe when either side is the -1e30 'empty' partial
    (its weight underflows to exactly 0; the double-empty case keeps the
    zero output)."""
    lse_m = jnp.logaddexp(lse_a, lse_b)
    tr = lambda w: jnp.transpose(w, (0, 2, 1))[..., None]  # noqa: E731
    out = (out_a * tr(jnp.exp(lse_a - lse_m))
           + out_b * tr(jnp.exp(lse_b - lse_m)))
    return out, lse_m


def _hop_bwd(q, kc, vc, out, lse128, g, hop_idx, interpret):
    """Gradients of one hop against the GLOBAL logsumexp, via the Pallas
    FlashAttention-2 backward kernels.  All inputs BSHD; returns f32
    (dq [B,Sq,nh,hd], dk [B,C,nkv,hd], dv [B,C,nkv,hd])."""
    B, Sq, nh, hd = q.shape
    C, nkv = kc.shape[1], kc.shape[2]

    def skip(q, kc, vc, out, g):
        return (jnp.zeros((B, Sq, nh, hd), jnp.float32),
                jnp.zeros((B, C, nkv, hd), jnp.float32),
                jnp.zeros((B, C, nkv, hd), jnp.float32))

    def mk(causal):
        def run(q, kc, vc, out, g):
            dq, dk, dv = pallas_flash.flash_attention_bwd(
                q, kc, vc, out, lse128, g, causal=causal,
                interpret=interpret)
            return (dq.astype(jnp.float32), dk.astype(jnp.float32),
                    dv.astype(jnp.float32))
        return run

    return jax.lax.switch(hop_idx, (skip, mk(False), mk(True)),
                          q, kc, vc, out, g)


def _lse128(lse):
    """[B, nh, S] -> the [B, nh, S, 128] lane-broadcast layout the backward
    kernels read (they only consume lane 0)."""
    return jnp.broadcast_to(lse[..., None], lse.shape + (128,))


def _causal_hop_idx(src, rank):
    """Which hop kind a causal rank runs against the chunk that started on
    rank `src`: earlier chunks are fully visible, own chunk is the causal
    diagonal, later chunks are masked out entirely."""
    return jnp.where(src == rank, _DIAG,
                     jnp.where(src < rank, _FULL, _SKIP)).astype(jnp.int32)


def _pvary(*xs, axis_name):
    """Mark rank-invariant scan carries as varying over the manual axis so
    carry types match the rank-dependent updates (jax_compat dispatches
    the pcast/pvary spelling and no-ops on pre-vma jax)."""
    return tuple(_compat_pvary(x, (axis_name,)) for x in xs)


# ----------------------------------------------------- multi-device ring

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_core(q, k, v, axis_name, causal, interpret):
    out, _ = _ring_fwd(q, k, v, axis_name, causal, interpret)
    return out


def _ring_fwd(q, k, v, axis_name, causal, interpret):
    """BSHD ring forward inside shard_map: scan n hops, Pallas per hop,
    lse-merge between hops, K/V rotating via ppermute (uniform rotation so
    XLA pipelines hop i+1's permute under hop i's compute; n hops return
    the buffers home)."""
    n = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, S, nh, hd = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    out0 = jnp.zeros((B, S, nh, hd), jnp.float32)
    lse0 = jnp.full((B, nh, S), _NEG, jnp.float32)
    out0, lse0 = _pvary(out0, lse0, axis_name=axis_name)

    def hop(carry, i):
        out, lse, k_cur, v_cur = carry
        src = (rank - i) % n   # chunk resident after i hops started on src
        idx = _causal_hop_idx(src, rank) if causal else jnp.int32(_FULL)
        o_h, l_h = _hop_fwd(q, k_cur, v_cur, idx, interpret)
        out, lse = _merge(out, lse, o_h, l_h)
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        return (out, lse, k_cur, v_cur), None

    (out, lse, _, _), _ = jax.lax.scan(hop, (out0, lse0, k, v),
                                       jnp.arange(n))
    return out.astype(q.dtype), lse


def _ring_core_fwd(q, k, v, axis_name, causal, interpret):
    out, lse = _ring_fwd(q, k, v, axis_name, causal, interpret)
    return out, (q, k, v, out, lse)


def _ring_core_bwd(axis_name, causal, interpret, res, g):
    """Ring backward: K/V re-rotate with f32 dk/dv accumulators traveling
    alongside, so each chunk collects its gradient contributions from every
    rank and arrives home after the full rotation."""
    q, k, v, out, lse = res
    n = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    lse_b = _lse128(lse)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dq0, dk0, dv0 = _pvary(dq0, dk0, dv0, axis_name=axis_name)

    def hop(carry, i):
        dq, dk_cur, dv_cur, k_cur, v_cur = carry
        src = (rank - i) % n
        idx = _causal_hop_idx(src, rank) if causal else jnp.int32(_FULL)
        dq_h, dk_h, dv_h = _hop_bwd(q, k_cur, v_cur, out, lse_b, g, idx,
                                    interpret)
        dq = dq + dq_h
        dk_cur = dk_cur + dk_h
        dv_cur = dv_cur + dv_h
        k_cur, v_cur, dk_cur, dv_cur = (
            jax.lax.ppermute(x, axis_name, perm)
            for x in (k_cur, v_cur, dk_cur, dv_cur))
        return (dq, dk_cur, dv_cur, k_cur, v_cur), None

    (dq, dk, dv, _, _), _ = jax.lax.scan(
        hop, (dq0, dk0, dv0, k, v), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def _ring_local_jnp(q, k, v, axis_name, causal, scale):
    """jnp fallback (exact online softmax) for unsupported shapes.

    GQA kv heads rotate around the ring UNEXPANDED (nkv payloads) and are
    repeated per hop right before the block update — the ppermute traffic
    stays 1/(nh/nkv) of the expanded size."""
    _check_gqa(q.shape[1], k.shape[1])
    n = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc0 = jnp.zeros((B, H, S, D), jnp.float32)
    m0 = jnp.full((B, H, S), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    acc0, m0, l0 = _pvary(acc0, m0, l0, axis_name=axis_name)

    def hop(carry, i):
        acc, m, l, k_cur, v_cur = carry
        src = (rank - i) % n
        ke, ve = _expand_kv_heads(q, k_cur, v_cur)
        acc, m, l = _block_update(q, ke, ve, acc, m, l,
                                  q_off=rank * S, k_off=src * S,
                                  causal=causal, scale=scale)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, m, l, k_nxt, v_nxt), None

    (acc, m, l, _, _), _ = jax.lax.scan(
        hop, (acc0, m0, l0, k, v), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype)


def ring_attention_local(q, k, v, axis_name: str, causal: bool = False,
                         scale: Optional[float] = None):
    """Exact attention where q/k/v are sequence-sharded over `axis_name`.

    Must run inside shard_map/pjit manual-sharding over `axis_name`.
    q, k, v: (B, H, S_local, D) — this rank's sequence slice.
    Returns (B, H, S_local, D) for this rank's queries over the FULL keys.

    Pallas flash kernels compute every hop when the shapes are in the
    kernels' envelope (head_dim 64/128/256, 8-aligned seqs); otherwise an
    exact jnp online-softmax path runs.
    """
    D = q.shape[-1]
    qs, ks, vs = (_bhsd_to_bshd(x) for x in (q, k, v))
    if _pallas_ok(qs.shape, ks.shape):
        if scale is not None and scale != D ** -0.5:
            # fold a custom scale into q so the kernels' 1/sqrt(hd) nets to
            # `scale`; AD of the pre-multiply restores the chain rule
            qs = qs * jnp.asarray(scale * D ** 0.5, qs.dtype)
        out = _ring_core(qs, ks, vs, axis_name, causal, None)
        return _bshd_to_bhsd(out)
    if scale is None:
        scale = D ** -0.5
    return _ring_local_jnp(q, k, v, axis_name, causal, scale)


def _ring_attention_val(q, k, v, mesh=None, axis_name="sp", causal=False,
                        scale=None):
    spec = P(None, None, axis_name, None)

    @functools.partial(
        _compat_shard_map, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
        # pallas_call outputs can't declare their varying mesh axes; skip
        # the vma check (the ring math is manifestly rank-varying)
        check_vma=False)
    def run(q, k, v):
        return ring_attention_local(q, k, v, axis_name, causal, scale)

    return run(q, k, v)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = False, scale: Optional[float] = None):
    """Convenience wrapper: shard q/k/v's sequence dim over `axis_name` of
    `mesh` and run `ring_attention_local` under shard_map.

    Accepts paddle Tensors or jax arrays of shape (B, H, S, D) with S
    divisible by the axis size.  Returns the same type as the input.
    Tensor inputs go through the op registry, so eager `loss.backward()`
    differentiates through the ring (AD of ppermute is the reverse permute).
    """
    from ....framework.tensor import Tensor
    from ....ops.registry import dispatch as _dispatch

    static = {"mesh": mesh, "axis_name": axis_name, "causal": causal,
              "scale": scale}
    if isinstance(q, Tensor):
        return _dispatch("ring_attention", (q, k, v), static)
    return _ring_attention_val(q, k, v, **static)




# ------------------------------------------------ single-device ring member

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _chunk_core(q, k, v, n_chunks, ja, causal, interpret):
    out, _ = _chunk_fwd_scan(q, k, v, n_chunks, ja, causal, interpret)
    return out


def _chunk_slices(k, v, n_chunks):
    """[B, S, nkv, hd] -> chunk-major [n, B, C, nkv, hd] scan inputs."""
    B, S, nkv, hd = k.shape
    C = S // n_chunks
    mk = lambda x: jnp.moveaxis(  # noqa: E731
        x.reshape(B, n_chunks, C, nkv, hd), 1, 0)
    return mk(k), mk(v)


def _chunk_fwd_scan(q, k, v, n_chunks, ja, causal, interpret):
    """One member q-chunk (BSHD, Sq == C, global chunk index `ja`) against
    all resident K/V chunks: the exact per-device hop program of
    `_ring_fwd`, with the ring rotation replaced by a scan over the chunk
    axis."""
    k5, v5 = _chunk_slices(k, v, n_chunks)

    B, Sq, nh, hd = q.shape
    out0 = jnp.zeros((B, Sq, nh, hd), jnp.float32)
    lse0 = jnp.full((B, nh, Sq), _NEG, jnp.float32)

    def hop(carry, xs):
        out, lse = carry
        i, kc, vc = xs
        idx = _causal_hop_idx(i, ja) if causal else jnp.int32(_FULL)
        o_h, l_h = _hop_fwd(q, kc, vc, idx, interpret)
        out, lse = _merge(out, lse, o_h, l_h)
        return (out, lse), None

    (out, lse), _ = jax.lax.scan(hop, (out0, lse0),
                                 (jnp.arange(n_chunks), k5, v5))
    return out.astype(q.dtype), lse


def _chunk_core_fwd(q, k, v, n_chunks, ja, causal, interpret):
    out, lse = _chunk_fwd_scan(q, k, v, n_chunks, ja, causal, interpret)
    return out, (q, k, v, out, lse)


def _chunk_core_bwd(n_chunks, ja, causal, interpret, res, g):
    """Member backward: re-scan the chunks with the Pallas FA2 backward
    kernels against the global logsumexp; per-chunk dk/dv emit as scan
    outputs (each key chunk's grad comes only from this member's queries),
    dq accumulates in f32."""
    q, k, v, out, lse = res
    lse_b = _lse128(lse)
    k5, v5 = _chunk_slices(k, v, n_chunks)
    dq0 = jnp.zeros(q.shape, jnp.float32)

    def hop(dq, xs):
        i, kc, vc = xs
        idx = _causal_hop_idx(i, ja) if causal else jnp.int32(_FULL)
        dq_h, dk_h, dv_h = _hop_bwd(q, kc, vc, out, lse_b, g, idx,
                                    interpret)
        return dq + dq_h, (dk_h, dv_h)

    dq, (dk5, dv5) = jax.lax.scan(hop, dq0,
                                  (jnp.arange(n_chunks), k5, v5))
    unchunk = lambda x5: jnp.moveaxis(x5, 0, 1).reshape(k.shape)  # noqa: E731
    return (dq.astype(q.dtype), unchunk(dk5).astype(k.dtype),
            unchunk(dv5).astype(v.dtype))


_chunk_core.defvjp(_chunk_core_fwd, _chunk_core_bwd)


def _chunked_jnp(q, k, v, n_chunks, causal, scale, q_off):
    """jnp fallback: the original exact online-softmax member program."""
    k, v = _expand_kv_heads(q, k, v)
    B, H, Sq, D = q.shape
    C = k.shape[2] // n_chunks
    kc = k.reshape(B, H, n_chunks, C, D)
    vc = v.reshape(B, H, n_chunks, C, D)

    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    m0 = jnp.full((B, H, Sq), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)

    def hop(carry, i):
        acc, m, l = carry
        acc, m, l = _block_update(
            q, kc[:, :, i], vc[:, :, i], acc, m, l,
            q_off=q_off, k_off=i * C, causal=causal, scale=scale)
        return (acc, m, l), None

    (acc, m, l), _ = jax.lax.scan(hop, (acc0, m0, l0),
                                  jnp.arange(n_chunks))
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype)


def ring_attention_chunked(q, k, v, n_chunks: int, causal: bool = False,
                           scale: Optional[float] = None, q_off: int = 0):
    """Single-device form of one ring member: the SAME hop program as the
    multi-device `ring_attention_local` (Pallas flash per K/V chunk, lse
    merge between hops), with the ring rotation replaced by a scan over the
    resident chunks.  q is this member's query slice (q_off = its absolute
    sequence offset, for the causal mask); k/v carry the FULL context.
    Scores only ever exist as VMEM-resident flash blocks — the memory shape
    that lets an n-device ring hold n× the context.

    q: (B, H, S_q, D); k, v: (B, H, S_k, D), S_k divisible by n_chunks.
    Exact (online softmax), matching the multi-device `ring_attention`
    hop-for-hop.
    """
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    C = Sk // n_chunks
    qs, ks, vs = (_bhsd_to_bshd(x) for x in (q, k, v))
    aligned = (C > 0 and Sq % C == 0 and q_off % C == 0
               and (not causal or q_off + Sq <= Sk))
    if aligned and _pallas_ok((B, C, H, D), (B, C, k.shape[1], D)):
        if scale is not None and scale != D ** -0.5:
            qs = qs * jnp.asarray(scale * D ** 0.5, qs.dtype)
        outs = []
        for a in range(Sq // C):   # static member q-chunks
            ja = q_off // C + a
            outs.append(_chunk_core(qs[:, a * C:(a + 1) * C], ks, vs,
                                    n_chunks, ja, causal, None))
        out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
        return _bshd_to_bhsd(out)
    if scale is None:
        scale = D ** -0.5
    return _chunked_jnp(q, k, v, n_chunks, causal, scale, q_off)


# ------------------------------------------------ Ulysses (head all-to-all)

def _dense_attention(q, k, v, causal, scale):
    """Dense BHSD attention for shapes outside the Pallas envelope."""
    k, v = _expand_kv_heads(q, k, v)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        S = q.shape[2]
        qpos = jax.lax.iota(jnp.int32, S)[:, None]
        kpos = jax.lax.iota(jnp.int32, S)[None, :]
        s = jnp.where(qpos >= kpos, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(p.dtype)).astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name: str, causal: bool = False,
                            scale: Optional[float] = None):
    """Ulysses / segment-parallel attention (the reference's `sep` axis:
    `fleet/base/topology.py` sep dim, `fleet/meta_parallel/
    segment_parallel.py`): q/k/v arrive sequence-sharded over `axis_name`;
    an all-to-all regroups them to head-sharded over the FULL sequence,
    plain (flash) attention runs locally on H/n heads, and the reverse
    all-to-all restores sequence sharding.  Two all-to-alls instead of a
    ring of ppermutes — the cheap option when num_heads % axis_size == 0.

    Must run inside shard_map over `axis_name`.
    q, k, v: (B, H, S_local, D); H divisible by the axis size.
    Returns (B, H, S_local, D).  Differentiable (all_to_all is its own
    transpose).
    """
    n = _axis_size(axis_name)
    B, H, Sl, D = q.shape
    if H % n or k.shape[1] % n:
        raise ValueError(
            f"ulysses_attention: num_heads ({H}) and kv heads "
            f"({k.shape[1]}) must be divisible by the '{axis_name}' axis "
            f"size ({n}); use ring_attention instead")
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            split_axis=1, concat_axis=2, tiled=True)
    qg, kg, vg = a2a(q), a2a(k), a2a(v)     # [B, H/n, S, D]
    qs = _bhsd_to_bshd(qg)
    if _pallas_ok(qs.shape, (B, kg.shape[2], kg.shape[1], D)):
        if scale is not None and scale != D ** -0.5:
            qs = qs * jnp.asarray(scale * D ** 0.5, qs.dtype)
        out = _bshd_to_bhsd(pallas_flash.flash_attention(
            qs, _bhsd_to_bshd(kg), _bhsd_to_bshd(vg), causal=causal))
    else:
        out = _dense_attention(qg, kg, vg, causal,
                               D ** -0.5 if scale is None else scale)
    # reverse regroup: scatter seq, gather heads
    return jax.lax.all_to_all(out, axis_name=axis_name, split_axis=2,
                              concat_axis=1, tiled=True)


def _ulysses_attention_val(q, k, v, mesh=None, axis_name="sep",
                           causal=False, scale=None):
    spec = P(None, None, axis_name, None)

    @functools.partial(
        _compat_shard_map, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    def run(q, k, v):
        return ulysses_attention_local(q, k, v, axis_name, causal, scale)

    return run(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str = "sep",
                      causal: bool = False, scale: Optional[float] = None):
    """Convenience wrapper: shard q/k/v's sequence dim over `axis_name` of
    `mesh` and run `ulysses_attention_local` under shard_map.  Same
    contract as `ring_attention` (Tensor inputs dispatch through the op
    registry for eager autograd)."""
    from ....framework.tensor import Tensor
    from ....ops.registry import dispatch as _dispatch

    static = {"mesh": mesh, "axis_name": axis_name, "causal": causal,
              "scale": scale}
    if isinstance(q, Tensor):
        return _dispatch("ulysses_attention", (q, k, v), static)
    return _ulysses_attention_val(q, k, v, **static)


_register()
