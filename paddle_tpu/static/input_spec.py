"""InputSpec: symbolic input signature for export/compilation.

Parity: `python/paddle/static/input/__init__.py` (InputSpec).
None dims become export-time symbolic dimensions (jax.export symbolic
shapes), so a saved model serves any batch size — the reference gets the
same effect from ir dynamic dims.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core import dtypes as _dtypes

__all__ = ["InputSpec"]


class InputSpec:
    def __init__(self, shape: Sequence[Optional[int]], dtype="float32",
                 name: Optional[str] = None, stop_gradient: bool = True):
        self.shape = tuple(shape)
        self.dtype = _dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), str(tensor.dtype), name)

    @classmethod
    def from_numpy(cls, ndarray: np.ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")
