"""Test configuration: run everything on a virtual 8-device CPU mesh.

This is the TPU build's "fake backend" (SURVEY.md §4): distributed tests
exercise real XLA collectives over 8 virtual CPU devices, the same way the
reference's CI uses the custom_cpu plugin (`test/custom_runtime/`).  Bench
runs (bench.py) use the real TPU chip instead.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy tests excluded from the tier-1 fast run "
        "(`-m 'not slow'`); a plain pytest invocation runs everything")


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)
    yield


@pytest.fixture()
def hybrid_mesh():
    """dp2 x mp2 x sharding2 hybrid topology over the 8-device CPU mesh."""
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 2,
                               "sep_degree": 1}
    yield fleet.init(is_collective=True, strategy=strategy)
