"""Fleet telescope (ISSUE 17): cross-process distributed tracing,
fleet-wide metrics federation, and SLO burn-rate driven cordoning.

Fast layers — pure math (trace header grammar, ClockSync min-RTT
filter, DDSketch wire state + merge-vs-union rank error, burn-rate
windowed math with injected clocks), stub replicas (trace header
propagation through the router proxy, /fleet/metrics Prometheus
rendering against a hand-merged sketch, auto-cordon + recovery off
crafted /metrics/snapshot documents), and synthetic flight dumps
(fleet_trace multi-process merge + the `dump --fleet-trace` CLI).
The @slow layer is the burn-rate chaos drill: concurrent /generate
traffic stays 200 while the burn monitor cordons the burning replica
and lifts the cordon after recovery — zero dropped streams.
"""

import io
import json
import threading
import time
from contextlib import redirect_stderr, redirect_stdout
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import pytest

from paddle_tpu.flags import flag_guard, get_flag
from paddle_tpu.inference.fleet import FleetRouter, hand_off
from paddle_tpu.inference.fleet.router import predict_ttft_s
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.observability import dump as _dump
from paddle_tpu.observability import federation as _federation
from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import http as _http
from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import tracing as _tracing
from paddle_tpu.observability.quantiles import QuantileSketch

SSE_PAYLOAD = (b'data: {"token": 7, "n": 0}\n\n'
               b'event: done\n'
               b'data: {"rid": 1, "outcome": "finished", '
               b'"output_ids": [7]}\n\n')

READY_DOC = {"ready": True, "running": 0, "waiting": 0, "queue_depth": 0,
             "slots": 2, "free_slots": 2, "prefilling": 0,
             "ttft_evidence": {"admit_rate_per_s": 0.0,
                               "ttft_p50_s": 0.0, "samples": 0}}


class _TelescopeHandler(BaseHTTPRequestHandler):
    """Stub replica frontend: per-path canned GET docs, POST /generate
    records (headers, body) and replays a fixed SSE stream."""

    def log_message(self, format, *args):  # noqa: A002
        pass

    def _reply(self, code, ctype, body):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        for prefix in ("/metrics/snapshot", "/healthz"):
            if self.path.startswith(prefix):
                doc = self.server.docs.get(prefix)
                if doc is None:
                    self._reply(404, "application/json", b"{}")
                    return
                code = 200
                if prefix == "/healthz" and not doc.get("ready"):
                    code = 503
                self._reply(code, "application/json",
                            json.dumps(doc).encode())
                return
        self._reply(404, "application/json", b"{}")

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length") or 0)
        self.server.posts.append((dict(self.headers), self.rfile.read(n)))
        self._reply(200, "text/event-stream", SSE_PAYLOAD)


class _StubReplica:
    def __init__(self, healthz=None, snapshot=None):
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                          _TelescopeHandler)
        self._httpd.daemon_threads = True
        self._httpd.docs = {"/healthz": dict(healthz or READY_DOC)}
        if snapshot is not None:
            self._httpd.docs["/metrics/snapshot"] = snapshot
        self._httpd.posts = []
        self.port = self._httpd.server_address[1]
        self._t = threading.Thread(target=self._httpd.serve_forever,
                                   daemon=True)
        self._t.start()

    @property
    def addr(self):
        return f"127.0.0.1:{self.port}"

    @property
    def posts(self):
        return self._httpd.posts

    def set_snapshot(self, doc):
        self._httpd.docs["/metrics/snapshot"] = doc

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._t.join(timeout=5)


def _post_generate(port, prompt_ids, headers=None):
    conn = HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        h = {"Content-Type": "application/json"}
        h.update(headers or {})
        conn.request("POST", "/generate",
                     body=json.dumps({"prompt_ids": list(prompt_ids)}),
                     headers=h)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _snapshot_doc(outcomes=None, slo_viol=0, finished=0,
                  finished_tokens=0, registry=None):
    return {"schema": _federation.SNAPSHOT_SCHEMA,
            "unix_time": round(time.time(), 3), "pid": 1,
            "registry": registry or {},
            "engine": {"outcomes": dict(outcomes or {}),
                       "slo_violations_ttft": slo_viol,
                       "finished": finished,
                       "finished_tokens": finished_tokens,
                       "tpot_sketch": QuantileSketch().to_state(),
                       "ttft_evidence": {}}}


# ================================================ trace context grammar

def test_trace_header_mint_format_parse_roundtrip():
    t = _tracing.mint_trace_id()
    s = _tracing.new_span_id()
    assert len(t) == 16 and len(s) == 8
    assert _tracing.parse_header(_tracing.format_header(t, s)) == (t, s)
    assert _tracing.parse_header(_tracing.format_header(t)) == (t, None)
    # independent mints never collide in practice (and must differ here)
    assert _tracing.mint_trace_id() != t


def test_trace_header_malformed_inputs_never_raise():
    assert _tracing.parse_header(None) == (None, None)
    assert _tracing.parse_header("") == (None, None)
    assert _tracing.parse_header("zzzz") == (None, None)
    assert _tracing.parse_header("1234") == (None, None)    # trace too short
    # good trace, junk span: keep the trace, drop the span
    assert _tracing.parse_header("a" * 16 + "-XYZ") == ("a" * 16, None)
    # case/whitespace normalize
    assert _tracing.parse_header("  " + "A" * 16 + "-" + "B" * 8 + " ") \
        == ("a" * 16, "b" * 8)


def test_clock_sync_keeps_min_rtt_sample():
    cs = _tracing.ClockSync()
    assert cs.offset_s is None
    # rtt 0.2s, server 5s ahead of the midpoint
    assert cs.update(10.0, 15.1, 10.2) is True
    assert cs.offset_s == pytest.approx(5.0)
    assert cs.err_s == pytest.approx(0.1)
    # larger rtt: rejected, estimate unchanged
    assert cs.update(20.0, 99.0, 21.0) is False
    assert cs.offset_s == pytest.approx(5.0)
    # tighter rtt wins even with a different offset
    assert cs.update(30.0, 34.99, 30.02) is True
    assert cs.err_s == pytest.approx(0.01)
    assert cs.rtt_s == pytest.approx(0.02)
    # negative rtt (clock step mid-probe) is discarded
    assert cs.update(50.0, 55.0, 49.9) is False


# ================================================= sketch wire state

def test_sketch_state_roundtrip_and_merge_matches_union():
    import random
    rng = random.Random(17)
    a, b, union = QuantileSketch(), QuantileSketch(), QuantileSketch()
    for i in range(4000):
        v = rng.lognormvariate(0.0, 1.5)
        (a if i % 2 else b).add(v)
        union.add(v)
    # wire round-trip is exact
    back = QuantileSketch.from_state(a.to_state())
    assert back.count == a.count
    for q in (0.1, 0.5, 0.9, 0.99):
        assert back.quantile(q) == pytest.approx(a.quantile(q))
    # merge of independently-shipped states == union within the 1%
    # relative rank-error bound the DDSketch alpha guarantees
    merged = QuantileSketch.from_state(a.to_state())
    merged.merge(QuantileSketch.from_state(b.to_state()))
    assert merged.count == union.count
    for q in (0.1, 0.5, 0.9, 0.99):
        assert merged.quantile(q) == \
            pytest.approx(union.quantile(q), rel=0.021)


def test_empty_sketch_state_roundtrip():
    back = QuantileSketch.from_state(QuantileSketch().to_state())
    assert back.count == 0 and back.quantile(0.5) is None


# ================================================ federation merge

def _wire_counter(value, **labels):
    return {"kind": "counter", "help": "h",
            "series": [{"labels": [[k, v] for k, v in labels.items()],
                        "value": value}]}


def _wire_gauge(value, **labels):
    return {"kind": "gauge", "help": "h",
            "series": [{"labels": [[k, v] for k, v in labels.items()],
                        "value": value}]}


def _wire_sketch(sk, **labels):
    return {"kind": "quantile", "help": "h",
            "series": [{"labels": [[k, v] for k, v in labels.items()],
                        "sketch": sk.to_state()}]}


def test_merge_sums_counters_and_relabels_gauges():
    snaps = {
        "r0": {"registry": {
            "serving.requests": _wire_counter(3.0, outcome="finished"),
            "serving.queue_depth": _wire_gauge(2.0)}},
        "r1": {"registry": {
            "serving.requests": _wire_counter(4.0, outcome="finished"),
            "serving.queue_depth": _wire_gauge(7.0)}},
    }
    reg = _federation.merge_snapshots(snaps)
    c = reg.get("serving.requests")
    assert c.kind == "counter"
    assert c._series[(("outcome", "finished"),)] == pytest.approx(7.0)
    g = reg.get("serving.queue_depth")
    assert g._series[(("replica", "r0"),)] == pytest.approx(2.0)
    assert g._series[(("replica", "r1"),)] == pytest.approx(7.0)


def test_merge_sketches_by_bucket_addition():
    a, b, union = QuantileSketch(), QuantileSketch(), QuantileSketch()
    for i in range(1, 501):
        v = i / 1000.0
        (a if i % 2 else b).add(v)
        union.add(v)
    snaps = {"r0": {"registry": {"serving.ttft_seconds": _wire_sketch(a)}},
             "r1": {"registry": {"serving.ttft_seconds": _wire_sketch(b)}}}
    reg = _federation.merge_snapshots(snaps)
    lat = _federation.fleet_latency(reg)
    assert lat["ttft"]["count"] == union.count
    assert lat["ttft"]["p99_s"] == \
        pytest.approx(union.quantile(0.99), rel=0.021)
    assert lat["ttft"]["p50_s"] == \
        pytest.approx(union.quantile(0.5), rel=0.021)


def test_merge_skips_malformed_entries_and_kind_collisions():
    # replicas merge in sorted-name order: the first registration of a
    # metric fixes its kind, a later replica shipping the same name as a
    # DIFFERENT kind is skipped (one sick replica can't flip the fleet
    # view), and malformed series entries are dropped individually
    snaps = {
        "a_sick": {"registry": {
            "m.a": {"kind": "counter", "help": "h",
                    "series": [{"labels": "garbage", "value": 1.0}]},
            "m.b": None}},
        "b_ok": {"registry": {"m.a": _wire_counter(2.0)}},
        "c_collide": {"registry": {"m.a": _wire_gauge(9.0)}},
    }
    reg = _federation.merge_snapshots(snaps)
    m = reg.get("m.a")
    assert m.kind == "counter" and m._series[()] == pytest.approx(2.0)


def test_fleet_rendering_prefix_and_label_escaping():
    nasty = 'he said "hi"\\\n'
    snaps = {"r0": {"registry": {
        "serving.requests": _wire_counter(1.0, outcome=nasty),
        "serving.queue_depth": _wire_gauge(3.0)}}}
    text = _federation.render_fleet(_federation.merge_snapshots(snaps))
    assert "fleet_serving_requests" in text
    assert 'fleet_serving_queue_depth{replica="r0"} 3' in text
    # escaping: backslash, quote and newline all escaped in label values
    assert '\\"hi\\"' in text and "\\\\" in text and "\\n" in text
    assert "\nhe said" not in text     # the raw newline never leaks
    # every non-comment line parses as `name{...} value`
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        assert line.startswith("fleet_")
        assert line.rsplit(" ", 1)[1]


def test_local_snapshot_shape_and_engine_evidence():
    fake = SimpleNamespace(telemetry_snapshot=lambda: {"finished": 5})
    doc = _federation.local_snapshot(engine=fake)
    assert doc["schema"] == _federation.SNAPSHOT_SCHEMA
    assert doc["engine"] == {"finished": 5}
    assert isinstance(doc["registry"], dict)
    # a sick engine is dropped, not fatal
    def boom():
        raise RuntimeError("x")
    doc = _federation.local_snapshot(
        engine=SimpleNamespace(telemetry_snapshot=boom))
    assert "engine" not in doc


# ================================================ burn-rate monitor

def test_burn_rate_windowed_math():
    mon = _federation.BurnRateMonitor(fast_window_s=60, slow_window_s=600,
                                      threshold=2.0, error_budget=0.05)
    t0 = 1000.0
    mon.observe("r0", good=100, bad=0, now=t0)
    # 20% bad over the last 30s: burn = 0.2 / 0.05 = 4x in BOTH windows
    mon.observe("r0", good=180, bad=20, now=t0 + 30)
    assert mon.burn("r0", 60, now=t0 + 30) == pytest.approx(4.0)
    assert mon.burn("r0", 600, now=t0 + 30) == pytest.approx(4.0)
    assert mon.burning("r0", now=t0 + 30)
    # clean traffic afterwards: the fast window cools first
    mon.observe("r0", good=400, bad=20, now=t0 + 120)
    assert mon.burn("r0", 60, now=t0 + 120) == pytest.approx(0.0)
    assert mon.recovered("r0", now=t0 + 120)
    # ... while the slow window still remembers the spike
    assert mon.burn("r0", 600, now=t0 + 120) > 1.0
    assert not mon.burning("r0", now=t0 + 120)


def test_burn_rate_no_evidence_is_none_not_zero():
    mon = _federation.BurnRateMonitor()
    assert mon.burn("ghost", 60) is None
    assert not mon.burning("ghost") and not mon.recovered("ghost")
    mon.observe("r0", good=10, bad=0, now=1000.0)
    # no NEW events inside the window -> None (no evidence, no verdict)
    assert mon.burn("r0", 60, now=2000.0) is None
    view = mon.view(now=1000.0)
    assert set(view) == {"r0"}


def test_burn_rate_fast_spike_alone_does_not_cordon():
    # the slow window is the flap-guard: a 10s blip after a long clean
    # history burns the fast window but not the slow one
    mon = _federation.BurnRateMonitor(fast_window_s=60, slow_window_s=600,
                                      threshold=2.0, error_budget=0.05)
    t0 = 0.0
    mon.observe("r0", good=0, bad=0, now=t0)
    mon.observe("r0", good=5000, bad=0, now=t0 + 540)
    mon.observe("r0", good=5010, bad=10, now=t0 + 600)
    assert mon.burn("r0", 60, now=t0 + 600) >= 2.0
    assert mon.burn("r0", 600, now=t0 + 600) < 2.0
    assert not mon.burning("r0", now=t0 + 600)


# ====================================== predicted TTFT with live TPOT

def test_predict_ttft_tpot_capacity_caps_stale_admit_rate():
    # stale-high admission rate claims 50 admits/s; live decode evidence
    # says 2 slots each busy for avg 10 tokens * 0.1 s/token = 2 req/s
    stale = {"waiting": 10, "free_slots": 0, "slots": 2,
             "ttft_evidence": {"admit_rate_per_s": 50.0,
                               "ttft_p50_s": 0.1}}
    optimistic = predict_ttft_s(stale)
    with_tpot = dict(stale, ttft_evidence=dict(
        stale["ttft_evidence"], tpot_p50_s=0.1, avg_tokens_out=10.0))
    realistic = predict_ttft_s(with_tpot)
    # 11 positions / 2 req/s + base, vs 11/50 + base
    assert realistic == pytest.approx(0.1 + 11 / 2.0)
    assert optimistic == pytest.approx(0.1 + 11 / 50.0)
    assert realistic > optimistic * 5
    # capacity also substitutes when there is no admit rate at all
    no_rate = dict(with_tpot, ttft_evidence=dict(
        with_tpot["ttft_evidence"], admit_rate_per_s=0.0))
    assert predict_ttft_s(no_rate) == pytest.approx(0.1 + 11 / 2.0)
    # and without TPOT evidence the PR 16 model is untouched
    assert predict_ttft_s({"waiting": 3, "free_slots": 1,
                           "ttft_evidence": {"ttft_p50_s": 0.5}}) \
        == pytest.approx(2.0)


# =============================================== router trace threading

def test_router_mints_trace_and_forwards_header():
    stub = _StubReplica()
    router = FleetRouter({"r0": stub.addr}, port=0, poll_interval_s=30.0)
    try:
        status, body = _post_generate(router.port, [1, 2, 3])
        assert status == 200 and body == SSE_PAYLOAD
        headers, _ = stub.posts[0]
        trace_id, span = _tracing.parse_header(
            headers.get(_tracing.TRACE_HEADER))
        assert trace_id is not None and span is not None
        # the router's own flight recorder carries the matching spans
        spans = [e for e in router._flightrec().events()
                 if e.get("kind") == "span"
                 and e.get("trace_id") == trace_id]
        assert {e["name"] for e in spans} == {"plan", "proxy"}
        assert all(e["span"] == span for e in spans)
    finally:
        router.close()
        stub.close()


def test_router_adopts_client_trace_id():
    stub = _StubReplica()
    router = FleetRouter({"r0": stub.addr}, port=0, poll_interval_s=30.0)
    try:
        mine = "feedc0de" * 2
        status, _ = _post_generate(
            router.port, [4, 5],
            headers={_tracing.TRACE_HEADER: mine})
        assert status == 200
        headers, _ = stub.posts[0]
        got_trace, got_span = _tracing.parse_header(
            headers[_tracing.TRACE_HEADER])
        assert got_trace == mine          # adopted, not re-minted
        assert got_span is not None       # router hop appended its span
    finally:
        router.close()
        stub.close()


def test_router_flag_off_forwards_client_header_verbatim():
    stub = _StubReplica()
    with flag_guard(fleet_trace=False):
        router = FleetRouter({"r0": stub.addr}, port=0,
                             poll_interval_s=30.0)
        try:
            status, _ = _post_generate(router.port, [1])
            assert status == 200
            headers, _ = stub.posts[0]
            assert _tracing.TRACE_HEADER not in headers    # minted nothing
            mine = "ab" * 8
            _post_generate(router.port, [1],
                           headers={_tracing.TRACE_HEADER: mine})
            headers, _ = stub.posts[1]
            assert headers[_tracing.TRACE_HEADER] == mine  # verbatim
        finally:
            router.close()
    stub.close()


# ============================================== fleet metrics endpoint

def test_fleet_metrics_endpoint_renders_federated_view():
    sk = QuantileSketch()
    for i in range(1, 101):
        sk.add(i / 100.0)
    snap0 = _snapshot_doc(finished=3, registry={
        "serving.requests": _wire_counter(3.0, outcome="finished"),
        "serving.ttft_seconds": _wire_sketch(sk)})
    snap1 = _snapshot_doc(finished=4, registry={
        "serving.requests": _wire_counter(4.0, outcome="finished")})
    stubs = [_StubReplica(snapshot=snap0), _StubReplica(snapshot=snap1)]
    router = FleetRouter({"r0": stubs[0].addr, "r1": stubs[1].addr},
                         port=0, poll_interval_s=30.0)
    try:
        conn = HTTPConnection("127.0.0.1", router.port, timeout=10)
        conn.request("GET", "/fleet/metrics")
        resp = conn.getresponse()
        text = resp.read().decode()
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        conn.close()
        assert 'fleet_serving_requests{outcome="finished"} 7' in text
        # the federated p99 equals the sketch's own p99 (one replica
        # shipped the sketch, so federation must preserve it exactly)
        doc = router.describe()
        assert doc["fleet_latency"]["ttft"]["p99_s"] == \
            pytest.approx(sk.quantile(0.99))
        assert doc["fleet_latency"]["ttft"]["count"] == 100
    finally:
        router.close()
        for s in stubs:
            s.close()


class _FakeEngine:      # MetricsServer holds its engine by weakref
    def telemetry_snapshot(self):
        return {"finished": 9}


def test_metrics_snapshot_endpoint_serves_engine_evidence():
    fake = _FakeEngine()
    server = _http.MetricsServer(0, "127.0.0.1", engine=fake)
    try:
        conn = HTTPConnection("127.0.0.1", server.port, timeout=10)
        conn.request("GET", "/metrics/snapshot")
        resp = conn.getresponse()
        assert resp.status == 200
        doc = json.loads(resp.read())
        conn.close()
        assert doc["schema"] == _federation.SNAPSHOT_SCHEMA
        assert doc["engine"]["finished"] == 9
        assert isinstance(doc["registry"], dict)
    finally:
        server.close()


# ============================================ burn-driven auto-cordon

def test_router_auto_cordons_burning_replica_and_lifts_on_recovery():
    stubs = [_StubReplica(snapshot=_snapshot_doc(finished=100)),
             _StubReplica(snapshot=_snapshot_doc(finished=100))]
    with flag_guard(fleet_slo_burn_cordon=True,
                    fleet_burn_fast_window_s=60.0,
                    fleet_burn_slow_window_s=600.0):
        router = FleetRouter({"r0": stubs[0].addr, "r1": stubs[1].addr},
                             port=0, poll_interval_s=30.0)
        try:
            router.poll_metrics_all()           # baseline sample
            # r0 starts burning: 50 bad vs 50 good since baseline
            stubs[0].set_snapshot(_snapshot_doc(
                outcomes={"error": 40, "poisoned": 10}, finished=150))
            stubs[1].set_snapshot(_snapshot_doc(finished=200))
            router.poll_metrics_all()
            view = router.describe()["replicas"]
            assert view["r0"]["cordoned"] and view["r0"]["auto_cordoned"]
            assert not view["r1"]["cordoned"]
            assert view["r0"]["slo_burn"]["fast"] >= 2.0
            kinds = [e["kind"] for e in router._flightrec().events()]
            assert "slo_cordon" in kinds
            # traffic keeps flowing around the cordon
            status, _ = _post_generate(router.port, [1, 2, 3])
            assert status == 200
            assert len(stubs[1].posts) == 1 and not stubs[0].posts
            # r0 heals: clean events dominate the window again (all the
            # samples sit inside the fast window, so its baseline is the
            # first sample — recovery needs the bad FRACTION since then
            # back under the error budget)
            stubs[0].set_snapshot(_snapshot_doc(
                outcomes={"error": 40, "poisoned": 10}, finished=1500))
            router.poll_metrics_all()
            view = router.describe()["replicas"]
            assert not view["r0"]["cordoned"]
            assert "auto_cordoned" not in view["r0"]
            kinds = [e["kind"] for e in router._flightrec().events()]
            assert "slo_uncordon" in kinds
        finally:
            router.close()
    for s in stubs:
        s.close()


def test_burn_cordon_never_takes_the_last_replica():
    stub = _StubReplica(snapshot=_snapshot_doc(finished=10))
    with flag_guard(fleet_slo_burn_cordon=True):
        router = FleetRouter({"r0": stub.addr}, port=0,
                             poll_interval_s=30.0)
        try:
            router.poll_metrics_all()
            stub.set_snapshot(_snapshot_doc(
                outcomes={"error": 90}, finished=20))
            router.poll_metrics_all()
            view = router.describe()["replicas"]["r0"]
            assert not view["cordoned"]          # preference, not verdict
            assert view["slo_burn"]["fast"] >= 2.0
        finally:
            router.close()
    stub.close()


def test_manual_cordon_wins_over_burn_monitor():
    stub = _StubReplica(snapshot=_snapshot_doc(finished=10))
    router = FleetRouter({"r0": stub.addr, "r1": stub.addr}, port=0,
                         poll_interval_s=30.0)
    try:
        router.cordon("r0")
        # a manual cordon is never auto-lifted: the recovery path only
        # touches auto_cordoned cordons
        with flag_guard(fleet_slo_burn_cordon=True):
            router.poll_metrics_all()
            stub.set_snapshot(_snapshot_doc(finished=1000))
            router.poll_metrics_all()
        assert router.describe()["replicas"]["r0"]["cordoned"]
    finally:
        router.close()
        stub.close()


# ============================================== fleet timeline merge

def _router_flight_doc():
    rec = _flight.FlightRecorder()
    rec.record_event("replica_meta", replica="router")
    # router measured r0's clock 100s ahead (offset_s = replica - router)
    rec.record_event("clock_sync", replica="r0", offset_s=100.0,
                     err_s=0.001, rtt_s=0.002)
    rec.record_event("clock_sync", replica="r0", offset_s=90.0,
                     err_s=0.5, rtt_s=1.0)     # worse bound: ignored
    rec.record_span("plan", "router", 1000.0, 1000.01,
                    trace_id="a" * 16, span="b" * 8, home="r0",
                    degraded=False)
    rec.record_span("proxy", "router", 1000.01, 1000.5,
                    trace_id="a" * 16, span="b" * 8, replica="r0")
    return rec.snapshot(reason="test")


def _replica_flight_doc():
    rec = _flight.FlightRecorder()
    rec.record_event("replica_meta", replica="r0")
    # replica timestamps are in ITS clock: 100s ahead of the router
    rec.record_span("handoff_export", "handoff", 1100.1, 1100.2,
                    blocks=2, trace_id="a" * 16)
    rec.record_event("request", rid=1, outcome="finished", e2e_s=0.4,
                     queue_wait_s=0.0, prefill_s=0.1, ttft_s=0.1,
                     tokens_out=2, trace_id="a" * 16)
    return rec.snapshot(reason="test")


def test_fleet_trace_merges_processes_and_aligns_clocks():
    doc = _tracing.fleet_trace([_router_flight_doc(),
                                _replica_flight_doc()])
    other = doc["otherData"]
    assert other["schema"] == "paddle_tpu.fleet_trace/v1"
    assert [p["name"] for p in other["processes"]] == ["router", "r0"]
    assert other["processes"][1]["clock_offset_s"] == pytest.approx(100.0)
    assert other["trace_ids"] == ["a" * 16]
    evs = doc["traceEvents"]
    assert {e["pid"] for e in evs} == {1, 2}
    by_name = {e["name"]: e for e in evs if e.get("ph") == "X"}
    # the replica's export span lands ~0.09s after the router's proxy
    # span START despite its raw timestamp being 100s in the future
    assert by_name["handoff_export"]["pid"] == 2
    assert by_name["handoff_export"]["ts"] == pytest.approx(
        (1100.1 - 100.0) * 1e6, abs=1.0)
    assert by_name["proxy"]["ts"] == pytest.approx(1000.01 * 1e6, abs=1.0)
    # both processes carry the shared trace id in span args
    assert by_name["handoff_export"]["args"]["trace_id"] == "a" * 16
    assert by_name["plan"]["args"]["trace_id"] == "a" * 16
    # process_name metadata rows exist for both pids
    meta = [e for e in evs if e.get("name") == "process_name"]
    assert {e["args"]["name"] for e in meta} == {"router", "r0"}


def test_dump_fleet_trace_cli(tmp_path):
    d0, d1 = tmp_path / "router", tmp_path / "r0"
    d0.mkdir(), d1.mkdir()
    (d0 / "flight_0001.json").write_text(json.dumps(_router_flight_doc()))
    (d1 / "flight_0001.json").write_text(json.dumps(_replica_flight_doc()))
    out, err = io.StringIO(), io.StringIO()
    with redirect_stdout(out), redirect_stderr(err):
        rc = _dump.main(["--fleet-trace", str(d0), str(d1)])
    assert rc == 0
    doc = json.loads(out.getvalue())
    assert doc["otherData"]["schema"] == "paddle_tpu.fleet_trace/v1"
    assert doc["otherData"]["trace_ids"] == ["a" * 16]
    assert err.getvalue().count("(from ") == 2
    # a missing operand directory fails loudly with exit 1
    with redirect_stdout(io.StringIO()), redirect_stderr(io.StringIO()):
        assert _dump.main(["--fleet-trace", str(tmp_path / "ghost")]) == 1


# ======================================== handoff trace propagation

class _FakeRec:
    def __init__(self):
        self.spans = []

    def record_span(self, name, cat, start_s, end_s, **info):
        self.spans.append(dict(info, name=name, cat=cat))

    def record_event(self, kind, **info):
        pass


def test_hand_off_threads_trace_into_both_sides(tmp_path):
    src_rec, dst_rec = _FakeRec(), _FakeRec()
    src = SimpleNamespace(
        export_prefix_cache=lambda root: {"blocks": 2},
        release_exported_prefix=lambda: 2,
        _flightrec=lambda: src_rec)
    dst = SimpleNamespace(
        _import_prefix_cache=lambda root: None,
        _blocksan=None,
        _prefix_import_info={"blocks": 2},
        _flightrec=lambda: dst_rec)
    report = hand_off(src, dst, str(tmp_path), trace_id="c" * 16,
                      parent_span="d" * 8)
    assert report["trace_id"] == "c" * 16
    assert report["released_blocks"] == 2
    (exp,) = src_rec.spans
    (imp,) = dst_rec.spans
    assert exp["name"] == "handoff_export" and exp["cat"] == "handoff"
    assert imp["name"] == "handoff_import" and imp["cat"] == "handoff"
    assert exp["trace_id"] == imp["trace_id"] == "c" * 16
    assert exp["parent_span"] == imp["parent_span"] == "d" * 8
    # without context the spans still record, just untagged
    report = hand_off(src, dst, str(tmp_path))
    assert "trace_id" not in report
    assert "trace_id" not in src_rec.spans[-1]


# ======================================= auto chunks-per-tick budget

def _chunk_self(tpot_values=()):
    sk = QuantileSketch()
    for v in tpot_values:
        sk.add(v)
    return SimpleNamespace(_chunk_budget_now=None, _ev_tpot=sk)


def test_auto_chunk_budget_holds_without_slo_or_evidence():
    auto = ServingEngine._auto_chunk_budget
    with flag_guard(serving_tpot_slo_ms=0.0):
        assert auto(_chunk_self([0.1] * 100), 4) == 4    # no SLO: hold
    with flag_guard(serving_tpot_slo_ms=50.0):
        assert auto(_chunk_self([0.1] * 8), 4) == 4      # <16 samples


def test_auto_chunk_budget_walks_toward_the_slo():
    auto = ServingEngine._auto_chunk_budget
    with flag_guard(serving_tpot_slo_ms=50.0):
        # p90 of 100ms >> 50ms target: shrink one step per call, floor 1
        s = _chunk_self([0.1] * 32)
        assert auto(s, 4) == 3
        assert auto(s, 4) == 2
        assert auto(s, 4) == 1
        assert auto(s, 4) == 1
        # p90 of 10ms << half the target: grow back, capped at max
        fast = _chunk_self([0.01] * 32)
        fast._chunk_budget_now = 1
        assert auto(fast, 4) == 2
        assert auto(fast, 4) == 3
        assert auto(fast, 4) == 4
        assert auto(fast, 4) == 4
        # in the comfort band (between 0.5x and 1x target): hold
        mid = _chunk_self([0.04] * 32)
        mid._chunk_budget_now = 2
        assert auto(mid, 4) == 2
        # a lowered flag clamps a remembered higher budget
        s2 = _chunk_self([0.04] * 32)
        s2._chunk_budget_now = 4
        assert auto(s2, 2) == 2


def test_auto_chunk_flag_defaults():
    assert get_flag("serving_chunks_per_tick_auto") is False
    assert get_flag("fleet_trace") is True
    assert get_flag("fleet_metrics_interval_s") == 0.0
    assert get_flag("fleet_slo_burn_cordon") is False


# ==================================== @slow burn-rate chaos drill

@pytest.mark.slow
def test_burn_cordon_drill_zero_dropped_streams():
    """The acceptance drill: concurrent /generate traffic through the
    router while one replica's federated evidence starts burning, gets
    auto-cordoned, heals, and is un-cordoned — every stream answers 200
    throughout (zero dropped)."""
    stubs = [_StubReplica(snapshot=_snapshot_doc(finished=100))
             for _ in range(3)]
    results = []
    stop = threading.Event()

    def pound():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                status, _ = _post_generate(router.port, [i % 7, 3, 5])
                results.append(status)
            except OSError:
                results.append(-1)
            time.sleep(0.005)

    with flag_guard(fleet_slo_burn_cordon=True,
                    fleet_metrics_interval_s=0.05):
        router = FleetRouter({f"r{i}": s.addr
                              for i, s in enumerate(stubs)},
                             port=0, poll_interval_s=0.05)
        try:
            # baseline federation sweep FIRST: the burn math needs a
            # clean cumulative sample to delta against — injecting the
            # failure before the first sweep would make the burning
            # counts the baseline (no delta, no burn)
            router.poll_metrics_all()
            threads = [threading.Thread(target=pound, daemon=True)
                       for _ in range(3)]
            for t in threads:
                t.start()
            # phase 1: r0 burns; wait for the auto-cordon
            stubs[0].set_snapshot(_snapshot_doc(
                outcomes={"error": 50}, finished=150))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if router.describe()["replicas"]["r0"].get(
                        "auto_cordoned"):
                    break
                time.sleep(0.02)
            view = router.describe()["replicas"]["r0"]
            assert view["cordoned"] and view["auto_cordoned"]
            # phase 2: r0 heals; wait for the cordon to lift
            stubs[0].set_snapshot(_snapshot_doc(
                outcomes={"error": 50}, finished=1500))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if not router.describe()["replicas"]["r0"]["cordoned"]:
                    break
                time.sleep(0.02)
            assert not router.describe()["replicas"]["r0"]["cordoned"]
            stop.set()
            for t in threads:
                t.join(timeout=10)
        finally:
            stop.set()
            router.close()
    for s in stubs:
        s.close()
    assert results and all(s == 200 for s in results)
    kinds = [e["kind"] for e in router._flightrec().events()]
    assert "slo_cordon" in kinds and "slo_uncordon" in kinds
