"""Weight-only int8 quantized serving (`quantization/weight_only.py`
+ `inference/quant.py` — ISSUE 10).

Quantization is NOT lossless, so its contract is parity-BOUNDED: a
max-logit-deviation budget, greedy streams identical on (most of) the
smoke prompts, an honest weight-byte ratio in stats, and exact
bit-parity of everything that must not add further error on top —
TP degree 2 vs 1, spec decode vs plain, slicing vs re-quantizing.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import quant as squant
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
from paddle_tpu.quantization import dequantize_int8, quantize_absmax_int8


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt3_tiny())
    m.eval()
    return m


def test_quantize_roundtrip_error_bound_and_zero_channel():
    """Per-channel absmax int8: the dequant error of every element is
    at most half a quantization step of ITS channel; all-zero channels
    round-trip exactly."""
    rng = np.random.RandomState(0)
    w = (rng.randn(64, 48) * rng.rand(48) * 3).astype(np.float32)
    w[:, 7] = 0.0
    q, s = quantize_absmax_int8(w, axis=0)
    assert q.dtype == jnp.int8 and s.shape == (1, 48)
    dq = np.asarray(dequantize_int8(q, s))
    step = np.asarray(s)
    assert np.all(np.abs(dq - w) <= step / 2 + 1e-7)
    np.testing.assert_array_equal(dq[:, 7], 0.0)
    # symmetric: the -128 code is never produced
    assert int(np.asarray(q).min()) >= -127


def test_quantize_commutes_with_slicing():
    """The TP contract: per-channel independence makes
    quantize-then-slice == slice-then-quantize bit-for-bit along any
    non-reduced axis (how `quantize_plan` can quantize before
    `shard_plan` shards)."""
    rng = np.random.RandomState(1)
    w = rng.randn(32, 16).astype(np.float32)
    q, s = quantize_absmax_int8(w, axis=0)
    q2, s2 = quantize_absmax_int8(w[:, 8:], axis=0)
    np.testing.assert_array_equal(np.asarray(q)[:, 8:], np.asarray(q2))
    np.testing.assert_array_equal(np.asarray(s)[:, 8:], np.asarray(s2))
    # embedding variant: reduce over the hidden axis, slice vocab rows
    qe, se = quantize_absmax_int8(w, axis=1)
    qe2, se2 = quantize_absmax_int8(w[16:], axis=1)
    np.testing.assert_array_equal(np.asarray(qe)[16:], np.asarray(qe2))
    np.testing.assert_array_equal(np.asarray(se)[16:], np.asarray(se2))


def test_snapshot_selects_the_right_leaves(model):
    """2D matmul weights quantize (wte over the hidden axis), wpe and
    1D tensors stay fp, and the byte accounting is honest."""
    sd = model.state_dict()
    keys = sorted(sd)
    snap = squant.snapshot(keys, [sd[k]._value for k in keys])
    by_key = dict(zip(keys, snap.axes))
    assert by_key["gpt.wte.weight"] == 1
    assert by_key["gpt.wpe.weight"] is None
    assert by_key["gpt.blocks.0.attn.qkv.weight"] == 0
    assert by_key["gpt.blocks.0.mlp.fc1.weight"] == 0
    assert by_key["gpt.blocks.0.ln1.weight"] is None
    assert by_key["gpt.blocks.0.attn.qkv.bias"] is None
    st = snap.stats()
    assert st["quantized_tensors"] == sum(
        a is not None for a in snap.axes)
    assert st["ratio"] > 2.0      # fp32 -> int8 on the matmul bulk
    with pytest.raises(ValueError, match="serving_quant"):
        squant.snapshot(keys, [sd[k]._value for k in keys], "fp4")


def _streams(model, ps, budget=6, **kw):
    eng = ServingEngine(model, max_batch=3, max_context=128,
                        block_size=16, **kw)
    reqs = [eng.add_request(Request(p, max_new_tokens=budget))
            for p in ps]
    eng.run()
    return eng, [list(r.output_ids) for r in reqs]


@pytest.mark.slow  # 11s measured: compiles fp8 and fp32 engines back to back; quantization error-bound unit tests stay fast
def test_quant_parity_bounded(model):
    """The parity-bounded acceptance: logit deviation under a budget,
    and greedy token streams identical on the smoke prompts (an
    UNTRAINED tiny model's argmax gaps sit near the int8 noise floor,
    so a near-tie may flip — most streams must still match exactly; a
    trained model's gaps dwarf the deviation budget)."""
    sd = model.state_dict()
    keys = sorted(sd)
    snap = squant.snapshot(keys, [sd[k]._value for k in keys])
    deq = squant.dequant_values(snap.values, snap.axes)
    rng = np.random.RandomState(7)
    ids = paddle.to_tensor(rng.randint(1, 1000, (2, 16)).astype(np.int32))
    ref = np.asarray(model(ids)._value)
    orig = {k: sd[k]._value for k in keys}
    try:
        for k, v in zip(keys, deq):
            sd[k]._value = v
        got = np.asarray(model(ids)._value)
    finally:
        for k in keys:
            sd[k]._value = orig[k]
    dev = np.abs(ref - got).max()
    assert dev < 0.05, dev        # measured ~0.014 on this preset
    ps = [rng.randint(1, 1000, (L,)) for L in (9, 14, 21, 33, 11, 26)]
    _, fp = _streams(model, ps)
    eng, q = _streams(model, ps, quant="int8")
    matches = sum(a == b for a, b in zip(fp, q))
    assert matches >= 4, (matches, fp, q)
    st = eng.stats()["quant"]
    assert st["mode"] == "int8" and st["ratio"] > 2.0
    assert st["weight_bytes"] < st["fp_weight_bytes"]
    assert eng.stats()["free_blocks"] == eng.num_blocks


@pytest.mark.slow   # compiles the TP program grid; full runs cover it
def test_quant_tp2_bit_identical_to_tp1(model):
    """Quantize-then-shard: TP degree 2 quantized streams are
    BIT-identical to degree 1 quantized (no additional error beyond
    the one quantization), and the plan accounting matches."""
    rng = np.random.RandomState(9)
    ps = [rng.randint(1, 1000, (L,)) for L in (10, 25)]
    eng1, q1 = _streams(model, ps, budget=8, quant="int8")
    eng2, q2 = _streams(model, ps, budget=8, quant="int8", tp_degree=2)
    assert q2 == q1
    assert eng2.stats()["quant"] == eng1.stats()["quant"]


# ------------------------------------------------------ ISSUE 13: fp8

def test_quantize_fp8_roundtrip_and_slice_commute():
    """fp8 (e4m3fn) twin of the int8 contract pins: bounded RELATIVE
    per-channel error (3 mantissa bits -> 2^-4 half-step), all-zero
    channels exact, out-of-range never NaN (the pre-cast clip), and
    slice-commutes bit-for-bit along non-reduced axes — the TP
    quantize-then-shard contract, format #2."""
    from paddle_tpu.quantization import quantize_absmax_fp8
    from paddle_tpu.quantization.weight_only import FP8_MAX, HAS_FP8
    if not HAS_FP8:
        pytest.skip("jax build has no float8_e4m3fn")
    rng = np.random.RandomState(0)
    w = (rng.randn(64, 48) * rng.rand(48) * 3).astype(np.float32)
    w[:, 7] = 0.0
    q, s = quantize_absmax_fp8(w, axis=0)
    assert str(q.dtype) == "float8_e4m3fn" and s.shape == (1, 48)
    dq = np.asarray(dequantize_int8(q, s))       # generic dequant
    assert np.isfinite(dq).all()
    # e4m3 round-to-nearest: relative error <= 2^-4 of each element
    # magnitude + the subnormal floor of the channel's scale
    tol = np.abs(w) * 2.0 ** -4 + np.asarray(s) * 2.0 ** -9
    assert np.all(np.abs(dq - w) <= tol)
    np.testing.assert_array_equal(dq[:, 7], 0.0)
    # channel max lands exactly on +-FP8_MAX codes — never NaN
    assert np.abs(np.asarray(q, np.float32)).max() <= FP8_MAX
    # slice-commute along the non-reduced axis, both reduction flavors
    q2, s2 = quantize_absmax_fp8(w[:, 8:], axis=0)
    np.testing.assert_array_equal(np.asarray(q)[:, 8:].view(np.uint8),
                                  np.asarray(q2).view(np.uint8))
    np.testing.assert_array_equal(np.asarray(s)[:, 8:], np.asarray(s2))
    qe, se = quantize_absmax_fp8(w, axis=1)
    qe2, se2 = quantize_absmax_fp8(w[16:], axis=1)
    np.testing.assert_array_equal(np.asarray(qe)[16:].view(np.uint8),
                                  np.asarray(qe2).view(np.uint8))
    np.testing.assert_array_equal(np.asarray(se)[16:], np.asarray(se2))


@pytest.mark.slow   # engine build + dequant forwards (~3.4s);
                    # tier-1's thin margin keeps only the pure-math
                    # fp8 pins fast; full runs cover it
def test_fp8_parity_bounded_and_engine_stats(model):
    """fp8's own parity budget: max logit deviation < 0.25 on the
    smoke preset (measured ~0.07 — coarser than int8's 0.014/0.05 by
    the mantissa-width ratio, as documented), and the serving engine
    reports the fp8 mode + byte ratio in stats()['quant']."""
    from paddle_tpu.quantization.weight_only import HAS_FP8
    if not HAS_FP8:
        pytest.skip("jax build has no float8_e4m3fn")
    sd = model.state_dict()
    keys = sorted(sd)
    snap = squant.snapshot(keys, [sd[k]._value for k in keys], "fp8")
    assert snap.stats()["mode"] == "fp8"
    deq = squant.dequant_values(snap.values, snap.axes)
    rng = np.random.RandomState(7)
    ids = paddle.to_tensor(rng.randint(1, 1000, (2, 16)).astype(np.int32))
    ref = np.asarray(model(ids)._value)
    orig = {k: sd[k]._value for k in keys}
    try:
        for k, v in zip(keys, deq):
            sd[k]._value = v
        got = np.asarray(model(ids)._value)
    finally:
        for k in keys:
            sd[k]._value = orig[k]
    dev = np.abs(ref - got).max()
    assert dev < 0.25, dev        # measured ~0.072 on this preset
    ps = [rng.randint(1, 1000, (L,)) for L in (9, 14, 21)]
    eng, q = _streams(model, ps, quant="fp8")
    assert all(len(s) == 6 for s in q)
    st = eng.stats()["quant"]
    assert st["mode"] == "fp8" and st["ratio"] > 2.0
    assert st["weight_bytes"] < st["fp_weight_bytes"]
    assert eng.stats()["free_blocks"] == eng.num_blocks


@pytest.mark.slow   # compiles the TP program grid; full runs cover it
def test_fp8_tp2_bit_identical_to_tp1(model):
    """ISSUE 13 acceptance: fp8 quantize-then-shard == shard-then-
    quantize — TP degree 2 fp8 streams BIT-identical to degree 1 fp8
    (per-channel independence holds for the fp8 cast exactly as for
    int8 rounding), with matching plan accounting."""
    from paddle_tpu.quantization.weight_only import HAS_FP8
    if not HAS_FP8:
        pytest.skip("jax build has no float8_e4m3fn")
    rng = np.random.RandomState(9)
    ps = [rng.randint(1, 1000, (L,)) for L in (10, 25)]
    eng1, q1 = _streams(model, ps, budget=8, quant="fp8")
    eng2, q2 = _streams(model, ps, budget=8, quant="fp8", tp_degree=2)
    assert q2 == q1
    assert eng2.stats()["quant"] == eng1.stats()["quant"]
    assert eng1.stats()["quant"]["mode"] == "fp8"


@pytest.mark.slow   # two engine builds (~6s); full runs cover it
def test_fp8_composes_with_ngram_spec(model):
    """fp8 x model-free drafting: greedy streams equal the fp8-only
    engine (losslessness is relative to the engine's own weights),
    with both subsystems' stats populated."""
    from paddle_tpu.quantization.weight_only import HAS_FP8
    if not HAS_FP8:
        pytest.skip("jax build has no float8_e4m3fn")
    rng = np.random.RandomState(11)
    ps = [rng.randint(1, 1000, (L,)) for L in (12, 28)]
    _, q = _streams(model, ps, budget=8, quant="fp8")
    eng, sq = _streams(model, ps, budget=8, quant="fp8",
                       spec_decode=True, spec_draft="ngram", spec_k=3)
    assert sq == q
    st = eng.stats()
    assert st["speculative"]["ticks"] > 0
    assert st["speculative"]["draft"] == "ngram"
    assert st["quant"]["mode"] == "fp8"


@pytest.mark.slow   # 9.4s measured (PR 14 re-budget): spec x quant is
                    # also pinned by the @slow TP2/ngram compositions
                    # and gated hard in the spec_decode bench rung
def test_quant_composes_with_spec_decode(model):
    """spec x quant: the draft and target both serve from int8
    snapshots and the greedy streams equal the quant-only engine
    (losslessness is relative to the engine's own weights)."""
    paddle.seed(0)
    draft = GPTForCausalLM(gpt3_tiny())
    draft.eval()
    rng = np.random.RandomState(11)
    ps = [rng.randint(1, 1000, (L,)) for L in (12, 28)]
    _, q = _streams(model, ps, budget=8, quant="int8")
    eng, sq = _streams(model, ps, budget=8, quant="int8",
                       draft_model=draft, spec_decode=True, spec_k=3)
    assert sq == q
    st = eng.stats()
    assert st["speculative"]["ticks"] > 0
    assert st["speculative"]["accept_rate"] == 1.0
    assert st["quant"]["mode"] == "int8"
