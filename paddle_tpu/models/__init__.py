from .gpt import (GPTConfig, GPTForCausalLM, GPTModel, gpt3_1p3b,  # noqa: F401
                  gpt3_6p7b, gpt3_124m, gpt3_350m, gpt3_tiny)
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,  # noqa: F401
                    llama2_7b, llama2_13b, llama_tiny)
from .bert import (BertConfig, BertForMaskedLM,  # noqa: F401
                   BertForSequenceClassification, BertModel, bert_base,
                   bert_tiny)
