"""Weight-only int8 / fp8 quantization for the inference path.

Parity seat: the reference's weight-only quantized inference ops
(`paddle/phi/kernels/fusion/gpu/fused_weight_only_linear_pass` family,
AWQ/GPTQ-style deployment in PaddleNLP): matmul weights are stored as
int8 with per-output-channel absmax scales and dequantized inside the
compiled matmul, trading a cheap elementwise multiply for ~4x less
weight memory (fp32 baseline; the reference counts ~2x from fp16).

TPU-native shape: quantization happens ONCE at engine weight-snapshot
time (host side); the int8 tensor + scale ride into the compiled
program as inputs, and `dequantize_int8` runs INSIDE the traced
program, so XLA fuses the scale multiply into the consumer matmul and
device weight residency is int8.

Two storage formats share the one contract:

* **int8** — symmetric absmax codes; lowest error for weights whose
  channel distribution is roughly uniform in magnitude (7 bits of
  uniform resolution per channel).
* **fp8 (e4m3fn)** — per-channel absmax scaled into the +-448 finite
  range, stored as ``float8_e4m3fn``.  Same byte footprint as int8;
  the 4-bit exponent keeps RELATIVE precision across ~18 octaves, so
  small-magnitude weights inside a large-absmax channel (exactly where
  absmax-int8 rounds hardest) survive better, and on fp8-matmul
  hardware the dequant multiply can fold into the MXU's scaled-fp8
  path rather than an int->float convert.  Guarded: jax builds without
  ``jnp.float8_e4m3fn`` raise at quantize time (the serving flag
  surfaces that as a construction error, never a silent fp32 serve).

The per-channel contract that makes tensor-parallel slicing safe:
scales keep their reduced axis (``keepdims=True``), so a scale tensor
has exactly the weight's rank with size 1 on the reduction axis.
Because every channel is quantized independently (int8 rounding and
the fp8 cast are both elementwise given the channel scale), slicing
along any NON-reduced axis commutes with quantization bit-for-bit:
``quantize(w)[..., s]  ==  quantize(w[..., s])`` — which is why a TP
plan can quantize first and shard after (inference/quant.py) and still
be bit-identical to a rank-local quantization, in either format.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["quantize_absmax_int8", "quantize_absmax_fp8", "dequantize",
           "dequantize_int8", "QMAX", "FP8_MAX", "HAS_FP8"]

QMAX = 127  # symmetric int8: the -128 code is never produced
FP8_MAX = 448.0             # largest finite float8_e4m3fn value
_FP8 = getattr(jnp, "float8_e4m3fn", None)
HAS_FP8 = _FP8 is not None


def quantize_absmax_int8(w, axis: int = 0):
    """Per-channel symmetric absmax int8 over the ``axis`` dimension
    (the matmul contraction axis, so each OUTPUT channel owns a scale).

    Returns ``(q, scale)``: ``q`` int8 with ``w``'s shape, ``scale``
    ``w``'s dtype with ``shape[axis] == 1`` (keepdims).  All-zero
    channels quantize to zeros with scale 1 (dequant stays exact).
    """
    w = jnp.asarray(w)
    absmax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / QMAX, 1).astype(w.dtype)
    q = jnp.clip(jnp.round(w / scale), -QMAX, QMAX).astype(jnp.int8)
    return q, scale


def quantize_absmax_fp8(w, axis: int = 0):
    """Per-channel absmax fp8 (e4m3fn) over the ``axis`` dimension:
    each channel is scaled into the +-448 finite range and cast.

    Returns ``(q, scale)`` with the int8 twin's exact shape contract
    (``q`` fp8 with ``w``'s shape, keepdims ``scale`` in ``w``'s
    dtype).  The pre-cast clip matters: the e4m3fn conversion does NOT
    saturate — an out-of-range value becomes NaN, and float division
    can land ``absmax / scale`` a ULP above 448."""
    if not HAS_FP8:
        raise RuntimeError(
            "this jax build has no jnp.float8_e4m3fn; fp8 weight-only "
            "quantization is unavailable (use int8)")
    w = jnp.asarray(w)
    absmax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / FP8_MAX, 1).astype(w.dtype)
    q = jnp.clip(w / scale, -FP8_MAX, FP8_MAX).astype(_FP8)
    return q, scale


def dequantize(q, scale):
    """``q * scale`` back in the scale's (original weight) dtype; traced
    inside compiled programs so XLA fuses it into the consuming matmul.
    Format-agnostic: int8 and fp8 codes dequantize identically."""
    return (q.astype(scale.dtype) * scale)


# the historical int8-specific name; the math never was int8-specific
dequantize_int8 = dequantize
