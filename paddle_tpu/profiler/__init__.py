"""Profiler.  Parity: `python/paddle/profiler/__init__.py`."""

from .profiler import (Profiler, ProfilerState, ProfilerTarget, RecordEvent,
                       SummaryView, export_chrome_tracing, make_scheduler)

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "SummaryView", "make_scheduler", "export_chrome_tracing"]
