"""Hybrid-parallel topology.

Parity: `python/paddle/distributed/fleet/base/topology.py` (CommunicateTopology
`:65`, HybridCommunicateGroup `:178`, dims ["data","pipe","sharding","sep",
"model"] `:68`).

TPU-native: the topology IS a `jax.sharding.Mesh` with axes ordered
(pp, dp, sharding, sep, mp) — mp innermost so tensor-parallel collectives ride
the highest-bandwidth ICI links; pp outermost so pipeline p2p crosses the slow
links (the standard TPU layout, mirroring the reference's comm-group creation
order at `topology.py:290`).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import mesh as _mesh
from ..collective import Group, new_group
from ..env import get_rank, get_world_size

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "ParallelMode"]


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep",
                                           "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = None
        shape = tuple(dims)
        self._world = int(np.prod(shape))
        self._coords = np.indices(shape).reshape(len(shape), -1).T

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coord = [kwargs[n] for n in self._parallel_names]
        return int(np.ravel_multi_index(coord, tuple(self._dims)))

    def get_coord(self, rank):
        return tuple(np.unravel_index(rank, tuple(self._dims)))

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for r in range(self._world)
                if self.get_coord(r)[axis] == index]

    def get_comm_list(self, axis_name):
        """All rank-groups along `axis_name` (reference get_comm_list)."""
        axis = self._parallel_names.index(axis_name)
        others = [i for i in range(len(self._dims)) if i != axis]
        groups = {}
        for r in range(self._world):
            coord = self.get_coord(r)
            key = tuple(coord[i] for i in others)
            groups.setdefault(key, []).append(r)
        return list(groups.values())


# mesh axis order: pp outermost ... mp innermost
_MESH_ORDER = ["pp", "dp", "sharding", "sep", "mp"]
_NAME_MAP = {"data": "dp", "pipe": "pp", "sharding": "sharding",
             "sep": "sep", "model": "mp"}


class HybridCommunicateGroup:
    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 dp_degree=1, mp_degree=1, pp_degree=1, sharding_degree=1,
                 sep_degree=1):
        if topology is not None:
            dims = {_NAME_MAP[n]: topology.get_dim(n)
                    for n in topology.get_hybrid_group_names()}
            dp_degree = dims.get("dp", 1)
            mp_degree = dims.get("mp", 1)
            pp_degree = dims.get("pp", 1)
            sharding_degree = dims.get("sharding", 1)
            sep_degree = dims.get("sep", 1)
        self._topo = topology
        self._dp_degree = dp_degree
        self._mp_degree = mp_degree
        self._pp_degree = pp_degree
        self._sharding_degree = sharding_degree
        self._sep_degree = sep_degree

        sizes = {"pp": pp_degree, "dp": dp_degree, "sharding": sharding_degree,
                 "sep": sep_degree, "mp": mp_degree}
        mesh = _mesh.build_mesh(sizes)
        _mesh.set_mesh(mesh)
        self.mesh = mesh

        self._dp_group = new_group(axis="dp")
        self._mp_group = new_group(axis="mp")
        self._pp_group = new_group(axis="pp")
        self._sharding_group = new_group(axis="sharding")
        self._sep_group = new_group(axis="sep")
        self.global_rank = get_rank()

    # ---- parallel mode
    def get_parallel_mode(self):
        if self._mp_degree > 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._sharding_degree > 1:
            return ParallelMode.SHARDING_PARALLEL
        return ParallelMode.DATA_PARALLEL

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # ---- accessors (parity with HybridCommunicateGroup)
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_rank(self):
        return self._dp_group.rank

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_rank(self):
        return self._mp_group.rank

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return 0

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_stage_id(self):
        return self._pp_group.rank

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_rank(self):
        return self._sharding_group.rank

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return 0

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_rank(self):
        return self._sep_group.rank

    def get_sep_parallel_group(self):
        return self._sep_group

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return self._pp_group
