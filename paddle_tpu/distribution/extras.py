"""Distribution zoo extensions: Binomial, Cauchy, ContinuousBernoulli,
MultivariateNormal, Independent, Transform zoo + TransformedDistribution.

Parity: `python/paddle/distribution/binomial.py`, `cauchy.py`,
`continuous_bernoulli.py`, `multivariate_normal.py`, `independent.py`,
`transform.py`, `transformed_distribution.py`.

Same conventions as `distributions.py`: sampling draws through the
framework PRNG; densities are paddle-op expressions so `log_prob`
differentiates; everything traces under jit.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from ..framework import random as _random
from ..framework.tensor import Tensor
from ..ops.registry import dispatch as _d, register_op
from .distribution import Distribution, _t

__all__ = ["Binomial", "Cauchy", "ContinuousBernoulli",
           "MultivariateNormal", "Independent", "TransformedDistribution",
           "Transform", "AffineTransform", "ExpTransform", "PowerTransform",
           "SigmoidTransform", "TanhTransform", "AbsTransform",
           "ChainTransform"]

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)

register_op("random_binomial",
            lambda n, probs, *, key, shape:
            jax.random.binomial(key, n, probs, shape=shape).astype(
                jnp.float32))


def _mvn_sample(loc, scale_tril, *, key, shape):
    batch = jnp.broadcast_shapes(loc.shape[:-1], scale_tril.shape[:-2])
    eps = jax.random.normal(key, tuple(shape) + batch + loc.shape[-1:],
                            loc.dtype)
    return loc + jnp.einsum("...ij,...j->...i", scale_tril, eps)


register_op("random_mvn", _mvn_sample)


class Binomial(Distribution):
    """Parity: `distribution/binomial.py` (total_count, probs)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(tuple(np.broadcast_shapes(
            self.total_count.shape, self.probs.shape)))

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1.0 - self.probs)

    def sample(self, shape: Sequence[int] = ()):
        out_shape = self._extend_shape(shape)
        with paddle.no_grad():
            return _d("random_binomial", (self.total_count, self.probs),
                      {"key": _random.next_key(),
                       "shape": tuple(out_shape)})

    def log_prob(self, value):
        value = _t(value)
        n, p = self.total_count, self.probs
        logc = (paddle.lgamma(n + 1.0) - paddle.lgamma(value + 1.0)
                - paddle.lgamma(n - value + 1.0))
        return logc + value * paddle.log(p) + (n - value) * paddle.log1p(-p)

    def entropy(self):
        # second-order Stirling approximation (reference uses the same
        # closed form for large n; exact sum for small n is data-dependent)
        n, p = self.total_count, self.probs
        return 0.5 * paddle.log(
            2.0 * math.pi * math.e * n * p * (1.0 - p) + 1e-8)


class Cauchy(Distribution):
    """Parity: `distribution/cauchy.py` (loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev")

    def rsample(self, shape: Sequence[int] = ()):
        out_shape = self._extend_shape(shape)
        u = paddle.rand(list(out_shape))
        return self.loc + self.scale * paddle.tan(
            math.pi * (u - 0.5))

    def log_prob(self, value):
        value = _t(value)
        z = (value - self.loc) / self.scale
        return -math.log(math.pi) - paddle.log(self.scale) \
            - paddle.log1p(z * z)

    def cdf(self, value):
        value = _t(value)
        return paddle.atan((value - self.loc) / self.scale) / math.pi + 0.5

    def entropy(self):
        return paddle.log(4.0 * math.pi * self.scale
                          * paddle.ones_like(self.loc))

    def kl_divergence(self, other: "Cauchy"):
        # closed form (Chyzak & Nielsen 2019), as the reference cites
        a = (self.scale + other.scale) ** 2 + (self.loc - other.loc) ** 2
        return paddle.log(a / (4.0 * self.scale * other.scale))


class ContinuousBernoulli(Distribution):
    """Parity: `distribution/continuous_bernoulli.py` (probs in (0,1))."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _t(probs)
        self._lims = lims
        super().__init__(tuple(self.probs.shape))

    def _outside(self):
        return (self.probs < self._lims[0]) | (self.probs > self._lims[1])

    def _log_norm(self):
        """log C(p); Taylor expansion near p=0.5 (the reference's trick —
        the exact form 0/0s there)."""
        p = self.probs
        safe = paddle.where(self._outside(), p,
                            paddle.full_like(p, self._lims[0] - 0.1))
        exact = paddle.log(
            paddle.abs(2.0 * paddle.atanh(1.0 - 2.0 * safe))
            / (paddle.abs(1.0 - 2.0 * safe) + 1e-30))
        x = p - 0.5
        taylor = math.log(2.0) + (4.0 / 3.0 + 104.0 / 45.0 * x * x) * x * x
        return paddle.where(self._outside(), exact, taylor)

    @property
    def mean(self):
        p = self.probs
        safe = paddle.where(self._outside(), p,
                            paddle.full_like(p, self._lims[0] - 0.1))
        exact = safe / (2.0 * safe - 1.0) + \
            1.0 / (2.0 * paddle.atanh(1.0 - 2.0 * safe))
        x = p - 0.5
        taylor = 0.5 + (1.0 / 3.0 + 16.0 / 45.0 * x * x) * x
        return paddle.where(self._outside(), exact, taylor)

    @property
    def variance(self):
        p = self.probs
        safe = paddle.where(self._outside(), p,
                            paddle.full_like(p, self._lims[0] - 0.1))
        t = paddle.atanh(1.0 - 2.0 * safe)
        exact = safe * (safe - 1.0) / (1.0 - 2.0 * safe) ** 2 \
            + 1.0 / (2.0 * t) ** 2
        x = (p - 0.5) ** 2
        taylor = 1.0 / 12.0 - (1.0 / 15.0 - 128.0 / 945.0 * x) * x
        return paddle.where(self._outside(), exact, taylor)

    def rsample(self, shape: Sequence[int] = ()):
        out_shape = self._extend_shape(shape)
        u = paddle.rand(list(out_shape))
        p = self.probs
        safe = paddle.where(self._outside(), p,
                            paddle.full_like(p, self._lims[0] - 0.1))
        # inverse CDF for p != 1/2; u itself at p == 1/2
        icdf = (paddle.log1p(u * (2.0 * safe - 1.0) / (1.0 - safe))
                / (paddle.log(safe) - paddle.log1p(-safe)))
        return paddle.where(self._outside(), icdf, u)

    def log_prob(self, value):
        value = _t(value)
        p = self.probs
        return value * paddle.log(p) + (1.0 - value) * paddle.log1p(-p) \
            + self._log_norm()

    def entropy(self):
        # E[-log p(X)] = -(C' terms); use mean identity
        m = self.mean
        p = self.probs
        return -(m * paddle.log(p) + (1.0 - m) * paddle.log1p(-p)
                 + self._log_norm())


class MultivariateNormal(Distribution):
    """Parity: `distribution/multivariate_normal.py` (loc + one of
    covariance_matrix / precision_matrix / scale_tril)."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _t(loc)
        given = sum(x is not None for x in
                    (covariance_matrix, precision_matrix, scale_tril))
        if given != 1:
            raise ValueError("give exactly one of covariance_matrix / "
                             "precision_matrix / scale_tril")
        if scale_tril is not None:
            self.scale_tril = _t(scale_tril)
        elif covariance_matrix is not None:
            self.scale_tril = paddle.linalg.cholesky(_t(covariance_matrix))
        else:
            prec = _t(precision_matrix)
            self.scale_tril = paddle.linalg.inv(
                paddle.linalg.cholesky(prec)).transpose(
                    perm=list(range(prec.ndim - 2)) + [prec.ndim - 1,
                                                       prec.ndim - 2])
        d = self.loc.shape[-1]
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.loc.shape[:-1]), tuple(self.scale_tril.shape[:-2]))),
            (d,))

    @property
    def covariance_matrix(self):
        lt = self.scale_tril
        perm = list(range(lt.ndim - 2)) + [lt.ndim - 1, lt.ndim - 2]
        return paddle.matmul(lt, lt.transpose(perm=perm))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return (self.scale_tril ** 2).sum(axis=-1)

    def rsample(self, shape: Sequence[int] = ()):
        return _d("random_mvn", (self.loc, self.scale_tril),
                  {"key": _random.next_key(), "shape": tuple(shape)})

    def _maha_and_logdet(self, value):
        diff = value - self.loc
        sol = paddle.linalg.triangular_solve(
            self.scale_tril, diff.unsqueeze(-1), upper=False).squeeze(-1)
        maha = (sol * sol).sum(axis=-1)
        logdet = paddle.log(paddle.abs(
            self.scale_tril.diagonal(axis1=-2, axis2=-1))).sum(axis=-1)
        return maha, logdet

    def log_prob(self, value):
        value = _t(value)
        d = self.loc.shape[-1]
        maha, logdet = self._maha_and_logdet(value)
        return -0.5 * maha - logdet - d * _HALF_LOG_2PI

    def entropy(self):
        d = self.loc.shape[-1]
        lt = self.scale_tril
        logdet = paddle.log(paddle.abs(
            lt.diagonal(axis1=-2, axis2=-1))).sum(axis=-1)
        return logdet + 0.5 * d * (1.0 + math.log(2.0 * math.pi))

    def kl_divergence(self, other: "MultivariateNormal"):
        d = self.loc.shape[-1]
        # tr(S2^-1 S1) + maha - d + logdet2 - logdet1
        sol = paddle.linalg.triangular_solve(
            other.scale_tril,
            self.scale_tril, upper=False)
        tr = (sol * sol).sum(axis=[-2, -1])
        maha, logdet2 = other._maha_and_logdet(self.loc)
        logdet1 = paddle.log(paddle.abs(
            self.scale_tril.diagonal(axis1=-2, axis2=-1))).sum(axis=-1)
        return 0.5 * (tr + maha - float(d)) + logdet2 - logdet1


class Independent(Distribution):
    """Reinterprets batch dims as event dims (`independent.py`)."""

    def __init__(self, base, reinterpreted_batch_rank: int, name=None):
        self.base = base
        self._rank = int(reinterpreted_batch_rank)
        bshape = tuple(base.batch_shape)
        if self._rank > len(bshape):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        super().__init__(bshape[:len(bshape) - self._rank],
                         bshape[len(bshape) - self._rank:]
                         + tuple(base.event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape: Sequence[int] = ()):
        return self.base.sample(shape)

    def rsample(self, shape: Sequence[int] = ()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        if self._rank == 0:
            return lp
        return lp.sum(axis=list(range(lp.ndim - self._rank, lp.ndim)))

    def entropy(self):
        ent = self.base.entropy()
        if self._rank == 0:
            return ent
        return ent.sum(axis=list(range(ent.ndim - self._rank, ent.ndim)))


# ----------------------------------------------------------------- transforms
class Transform:
    """Bijector base (`distribution/transform.py` Transform)."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return paddle.log(paddle.abs(self.scale)) * paddle.ones_like(x)


class ExpTransform(Transform):
    def forward(self, x):
        return paddle.exp(x)

    def inverse(self, y):
        return paddle.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _t(power)

    def forward(self, x):
        return x ** self.power

    def inverse(self, y):
        return y ** (1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        return paddle.log(paddle.abs(self.power * x ** (self.power - 1.0)))


class SigmoidTransform(Transform):
    def forward(self, x):
        return paddle.nn.functional.sigmoid(x)

    def inverse(self, y):
        return paddle.log(y) - paddle.log1p(-y)

    def forward_log_det_jacobian(self, x):
        return -paddle.nn.functional.softplus(-x) \
            - paddle.nn.functional.softplus(x)


class TanhTransform(Transform):
    def forward(self, x):
        return paddle.tanh(x)

    def inverse(self, y):
        return paddle.atanh(y)

    def forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2 (log 2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x
                      - paddle.nn.functional.softplus(-2.0 * x))


class AbsTransform(Transform):
    def forward(self, x):
        return paddle.abs(x)

    def inverse(self, y):
        return y  # principal branch

    def forward_log_det_jacobian(self, x):
        return paddle.zeros_like(x)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ld = t.forward_log_det_jacobian(x)
            total = ld if total is None else total + ld
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    """Pushforward of `base` through `transforms`
    (`transformed_distribution.py`)."""

    def __init__(self, base, transforms, name=None):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transforms = list(transforms)
        super().__init__(tuple(base.batch_shape), tuple(base.event_shape))

    def _chain(self):
        return ChainTransform(self.transforms)

    def sample(self, shape: Sequence[int] = ()):
        return self._chain().forward(self.base.sample(shape))

    def rsample(self, shape: Sequence[int] = ()):
        return self._chain().forward(self.base.rsample(shape))

    def log_prob(self, value):
        value = _t(value)
        chain = self._chain()
        x = chain.inverse(value)
        return self.base.log_prob(x) - chain.forward_log_det_jacobian(x)
