"""Step-level training telemetry: the StepTimeline.

PR 1 left the raw streams in place — span/step histograms, collective
byte/call counters, jit compile timers — but nothing turned them into
the per-step evidence the ROADMAP's "fast as the hardware allows" goal
needs (round 5's MFU number was defended by extrapolation).  The
StepTimeline closes that gap: it brackets each training step, diffs the
relevant registry streams across the bracket, and emits ONE
schema-stable record per step with

* wall seconds + the host/data gap since the previous step,
* compile seconds attributed to this step (``jit.compile_seconds``
  delta — trace + XLA compile both land there),
* collective calls/bytes delta and an estimated communication time
  (bytes / ICI bandwidth — an analytic estimate, labelled as such: XLA
  overlaps collectives with compute, so this is an upper bound on
  exposed comm).  Scope caveat: the counters live in the python-level
  ``distributed.collective`` API, so eager collectives count per call
  but collectives captured inside a jitted program count once at trace
  time (attributed to the compile step) and raw ``jax.lax`` collectives
  (the hybrid SPMD step) are not counted at all — for compiled training
  the comm fraction is a floor, not a measurement,
* compute/comm/host fractions of the step period (they sum to 1),
* tokens/sec and MFU from the ONE shared FLOPs helper
  (:mod:`.flops` — the same 6N + 12LHS accounting the models and the
  auto-tuner use).

Every record is also appended to the process flight recorder's ring
(:mod:`.flight_recorder`), so a crash dump always carries the last K
step timelines.  ``summary()`` aggregates the recorded steps into the
block bench artifacts embed (steady-state = steps without a compile).

Cost: creating a step bracket is a handful of registry reads under the
registry lock; with ``FLAGS_enable_metrics=0`` the bracket degenerates
to a shared no-op object and nothing is recorded.

Usage::

    from paddle_tpu.observability import telemetry

    tl = telemetry.StepTimeline(flops_per_token=model.flops_per_token(S),
                                device_kind="tpu v5e")
    for batch in loader:
        with tl.step(tokens=B * S) as st:
            loss = train_step(batch)
        st.annotate(loss=float(loss))
    print(tl.summary())
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from . import flops as _flops
from . import metrics as _metrics
from . import flight_recorder as _fr

__all__ = ["StepTimeline", "default_timeline", "TELEMETRY_SCHEMA"]

TELEMETRY_SCHEMA = "paddle_tpu.telemetry/v1"

# Default ICI payload bandwidth for the comm-time estimate (v5e public
# spec, same figure as the auto-tuner's Hardware default).
_DEFAULT_ICI_BW = 45e9


def _counter_total(name: str) -> float:
    m = _metrics.get(name)
    return m.total() if isinstance(m, _metrics.Counter) else 0.0


def _hist_totals(name: str):
    m = _metrics.get(name)
    if isinstance(m, _metrics.Histogram):
        return m.total_count(), m.total_sum()
    return 0, 0.0


class _NullStep:
    """The disabled-metrics bracket: every operation is a no-op."""

    __slots__ = ()
    tokens = 0
    loss = None
    index = -1
    synced = False

    def annotate(self, **kv) -> None:
        pass

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NullStep":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def __setattr__(self, name, value):  # tolerate `st.tokens = n` callers
        pass


_NULL_STEP = _NullStep()


class _Step:
    """One open step bracket; `end()` (or context exit) seals the record.

    ``synced`` marks that the caller forced a host materialization inside
    the bracket: on async backends an unsynced record's ``wall_s`` is
    ENQUEUE time (the device may still be running), so readers must treat
    tokens/sec and MFU from unsynced records as upper bounds.
    """

    __slots__ = ("_tl", "index", "tokens", "loss", "mode", "synced",
                 "_t0", "_gap_s", "_compile0", "_bytes0", "_calls0",
                 "_record", "_pending")

    def __init__(self, tl: "StepTimeline", index: int, tokens: int,
                 mode: Optional[str]):
        self._tl = tl
        self.index = index
        self.tokens = tokens
        self.loss: Optional[float] = None
        self.mode = mode
        self.synced = False
        self._pending: Dict[str, Any] = {}
        self._record: Optional[Dict[str, Any]] = None
        now = time.perf_counter()
        self._gap_s = (now - tl._last_end) if tl._last_end is not None else 0.0
        _, self._compile0 = _hist_totals("jit.compile_seconds")
        self._bytes0 = _counter_total("collective.bytes")
        self._calls0 = _counter_total("collective.calls")
        self._t0 = now

    def annotate(self, **kv) -> None:
        """Attach late measurements (loss lands after the step returns);
        before `end()` they seed the record, after it they update it in
        place — the flight ring holds the same dict, so dumps see them.
        Recording only: the NaN/Inf watchdog probe is the CALLER's
        `flight_recorder.check_finite`, which stays armed even when the
        metrics registry (and with it this timeline) is disabled."""
        if self._record is not None:
            self._record.update(kv)
            return
        for k, v in kv.items():
            if k in ("tokens", "loss", "mode", "synced"):
                setattr(self, k, v)
            else:
                # custom annotations (grad_norm, lr, ...) made inside
                # the bracket merge into the record when it seals
                self._pending[k] = v

    def end(self) -> Optional[Dict[str, Any]]:
        if self._record is not None:
            return self._record
        t1 = time.perf_counter()
        tl = self._tl
        tl._last_end = t1
        wall = max(t1 - self._t0, 1e-9)
        _, compile1 = _hist_totals("jit.compile_seconds")
        compile_s = max(compile1 - self._compile0, 0.0)
        comm_bytes = max(_counter_total("collective.bytes") - self._bytes0, 0)
        comm_calls = max(_counter_total("collective.calls") - self._calls0, 0)
        comm_est = comm_bytes / tl.ici_bandwidth if tl.ici_bandwidth else 0.0
        # fractions over the step PERIOD (gap + wall): host = data/input
        # gap + compile attributed to this step; comm = the analytic
        # estimate; compute = the remainder.  Clamped so they sum to 1.
        period = wall + self._gap_s
        host_s = min(self._gap_s + compile_s, period)
        comm_s = min(comm_est, period - host_s)
        compute_s = period - host_s - comm_s
        tps = self.tokens / wall if self.tokens else 0.0
        rec: Dict[str, Any] = {
            "schema": TELEMETRY_SCHEMA,
            "timeline": tl.name,
            "step": self.index,
            "wall_s": round(wall, 6),
            "gap_s": round(self._gap_s, 6),
            "compile_s": round(compile_s, 6),
            "comm_bytes": comm_bytes,
            "comm_calls": comm_calls,
            "comm_s_est": round(comm_s, 6),
            "tokens": self.tokens,
            "tokens_per_sec": round(tps, 1),
            "synced": bool(self.synced),
            "loss": self.loss,
            "fractions": {
                "compute": round(compute_s / period, 4),
                "comm": round(comm_s / period, 4),
                "host": round(host_s / period, 4),
            },
        }
        if self.mode is not None:
            rec["mode"] = self.mode
        if tl.flops_per_token and tl.peak_flops and self.tokens:
            rec["mfu"] = round(_flops.mfu(tps, tl.flops_per_token,
                                          peak=tl.peak_flops), 4)
        rec.update(self._pending)
        self._record = rec
        tl._append(rec)
        return rec

    def __enter__(self) -> "_Step":
        return self

    def __exit__(self, etype, exc, tb) -> bool:
        # a raising step still seals its record (partial evidence beats
        # none — the flight dump shows how far the step got)
        self.end()
        return False


class StepTimeline:
    """Per-step telemetry aggregator (see module docstring)."""

    def __init__(self, name: str = "train",
                 flops_per_token: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 device_kind: Optional[str] = None,
                 max_steps: int = 512,
                 ici_bandwidth: float = _DEFAULT_ICI_BW,
                 recorder: Optional[_fr.FlightRecorder] = None):
        self.name = name
        self.flops_per_token = flops_per_token
        if peak_flops is None and device_kind is not None:
            peak_flops = _flops.peak_flops(device_kind)
        self.peak_flops = peak_flops
        self.device_kind = device_kind
        self.max_steps = max(int(max_steps), 1)
        self.ici_bandwidth = ici_bandwidth
        self._recorder = recorder
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []
        self._count = 0
        self._last_end: Optional[float] = None

    def configure(self, *, flops_per_token: Optional[float] = None,
                  peak_flops: Optional[float] = None,
                  device_kind: Optional[str] = None) -> "StepTimeline":
        """Late-bind the MFU inputs (the model/device are often known
        only after the timeline's consumers started feeding it)."""
        if flops_per_token is not None:
            self.flops_per_token = flops_per_token
        if device_kind is not None:
            self.device_kind = device_kind
            if peak_flops is None:
                peak_flops = _flops.peak_flops(device_kind)
        if peak_flops is not None:
            self.peak_flops = peak_flops
        return self

    # ------------------------------------------------------------ recording
    def step(self, tokens: int = 0, mode: Optional[str] = None):
        """Open a step bracket (context manager or explicit ``end()``).
        Returns a shared no-op object when metrics are disabled."""
        if not _metrics.enabled():
            return _NULL_STEP
        with self._lock:
            idx = self._count
            self._count += 1
        return _Step(self, idx, tokens, mode)

    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._records.append(rec)
            del self._records[:-self.max_steps]
        recorder = self._recorder if self._recorder is not None \
            else _fr.default_recorder()
        recorder.record_step(rec)

    def annotate_last(self, **kv) -> Optional[Dict[str, Any]]:
        """Update the newest sealed record in place (loss etc. arriving
        after the bracket closed); returns that record so callers can
        anchor watchdog probes to its step index.  Recording only — the
        NaN/Inf probe is the caller's `check_finite`, kept independent
        of the metrics gate."""
        with self._lock:
            rec = self._records[-1] if self._records else None
        if rec is None:
            return None
        rec.update(kv)
        return rec

    # -------------------------------------------------------------- readout
    @property
    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._count = 0
            self._last_end = None

    def summary(self) -> Dict[str, Any]:
        """Aggregate the recorded steps: step-seconds stats, weighted
        fractions, steady-state tokens/sec and MFU (steady = steps with
        no compile charged, falling back to all steps)."""
        recs = self.records
        if not recs:
            # schema-stable zeros: a metrics-off run (the timeline is a
            # no-op) must not KeyError consumers reading the summary
            return {"schema": TELEMETRY_SCHEMA, "timeline": self.name,
                    "steps": 0, "steady_steps": 0, "synced_steps": 0,
                    "wall_s": 0.0,
                    "compile_s": 0.0, "comm_bytes": 0, "tokens": 0,
                    "tokens_per_sec": 0.0,
                    "step_seconds": {"mean": 0.0, "min": 0.0, "max": 0.0,
                                     "p50": 0.0},
                    "fractions": {"compute": 0.0, "comm": 0.0,
                                  "host": 0.0},
                    "loss_last": None}
        steady = [r for r in recs if r["compile_s"] < 1e-3] or recs
        walls = sorted(r["wall_s"] for r in steady)
        n = len(walls)
        period = sum(r["wall_s"] + r["gap_s"] for r in recs) or 1e-9
        frac = {k: round(sum(r["fractions"][k] * (r["wall_s"] + r["gap_s"])
                             for r in recs) / period, 4)
                for k in ("compute", "comm", "host")}
        tokens = sum(r["tokens"] for r in steady)
        wall_steady = sum(walls) or 1e-9
        tps = tokens / wall_steady
        out: Dict[str, Any] = {
            "schema": TELEMETRY_SCHEMA,
            "timeline": self.name,
            "steps": len(recs),
            "steady_steps": n,
            # async-step attribution: an unsynced record's wall_s is
            # ENQUEUE time (flag-spaced loss sync leaves the loss on
            # device), so tokens/sec from a mostly-unsynced timeline is
            # an upper bound — this count is the caveat's denominator
            "synced_steps": sum(1 for r in recs if r.get("synced")),
            "wall_s": round(sum(r["wall_s"] for r in recs), 6),
            "compile_s": round(sum(r["compile_s"] for r in recs), 6),
            "step_seconds": {"mean": round(wall_steady / n, 6),
                             "min": round(walls[0], 6),
                             "max": round(walls[-1], 6),
                             "p50": round(walls[n // 2], 6)},
            "comm_bytes": sum(r["comm_bytes"] for r in recs),
            "tokens": tokens,
            "tokens_per_sec": round(tps, 1),
            "fractions": frac,
            "loss_last": next((r["loss"] for r in reversed(recs)
                               if r.get("loss") is not None), None),
        }
        if self.flops_per_token and self.peak_flops:
            out["flops_per_token"] = self.flops_per_token
            out["peak_flops"] = self.peak_flops
            out["mfu"] = round(_flops.mfu(tps, self.flops_per_token,
                                          peak=self.peak_flops), 4)
        rec = self._recorder if self._recorder is not None \
            else _fr.default_recorder()
        if rec.first_nonfinite is not None:
            out["first_nonfinite"] = dict(rec.first_nonfinite)
        return out


# The process-default timeline the instrumented layers (hapi fit,
# fleet hybrid step) feed; bench and tests build their own instances.
_default: Optional[StepTimeline] = None
_default_lock = threading.Lock()


def default_timeline() -> StepTimeline:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = StepTimeline(name="train")
    return _default
