"""Atomic, versioned, integrity-checked checkpointing + auto-resume policy.

The in-place `save_state_dict` layout cannot survive a mid-write kill: a
truncated data file next to a valid-looking metadata file merges silently.
This module adds the orbax/torch-elastic-shaped commit protocol on top of
the same plan/write halves:

    root/
      step_00000042.tmp/        while saving (never read by loaders)
        0_0.distcp              rank data (save_state_dict layout)
        0.metadata
        extra_0.pkl             non-array leaves (coordinator rank only)
        manifest_0.json         per-rank manifest: sha256 + bytes per file
      step_00000042/            committed: atomic rename of the tmp dir
        ... + COMPLETE          sentinel written after ALL manifests validate

Commit order: every rank writes its files + manifest into the tmp dir; the
coordinator waits for all ranks' manifests, re-hashes every listed file,
atomically renames tmp → final and only then drops the `COMPLETE` sentinel.
A reader (`latest_complete`) accepts a version only if the sentinel exists
AND every manifest still validates — so truncation, bit flips and torn
tails are detected, skipped and reported, never silently loaded.

`CheckpointManager` owns the policy: save-every-K-steps, async save with a
synchronous device→host snapshot (the caller may donate buffers the moment
`save()` returns), keep-last-N rotation with keep-periodic retention,
transient-I/O retry with exponential backoff (`FLAGS_ckpt_io_retries` /
`FLAGS_ckpt_io_backoff_s`), and preemption handling (SIGTERM/SIGINT set a
flag; the train loop finishes the in-flight step, takes an emergency
checkpoint and exits cleanly).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import re
import shutil
import signal as _signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ... import flags as _flags
from ...framework.tensor import Tensor
from ...observability import flight_recorder as _flight
from ...observability import metrics as _metrics
from ...testing.chaos import checked_open
from . import save_state_dict as _sd
from .load_state_dict import load_state_dict, read_state_dict

__all__ = [
    "CheckpointManager", "latest_complete", "all_steps", "verify_version",
    "step_dir", "COMPLETE_SENTINEL", "MANIFEST_SCHEMA",
    "commit_single_rank",
    "preemption_requested", "request_preemption", "clear_preemption",
]

logger = logging.getLogger("paddle_tpu.checkpoint")

COMPLETE_SENTINEL = "COMPLETE"
MANIFEST_SCHEMA = "paddle_tpu.ckpt/v1"
_STEP_RE = re.compile(r"^step_(\d{8})$")

_M_SAVES = _metrics.counter(
    "ckpt.saves", "checkpoint save outcomes "
    "(result=committed|failed|skipped_existing)")
_M_BYTES = _metrics.counter(
    "ckpt.bytes_written", "checkpoint payload bytes written (data files)")
_M_RETRIES = _metrics.counter(
    "ckpt.io_retries", "transient-I/O retries during checkpoint writes "
    "(labels: site)")
_M_SKIP = _metrics.counter(
    "ckpt.skipped_corrupt", "checkpoint versions skipped by "
    "latest_complete (reason=incomplete|corrupt)")
_M_ROTATED = _metrics.counter(
    "ckpt.rotated", "checkpoint versions deleted by keep-last-N rotation")
_M_PREEMPT = _metrics.counter(
    "preempt.signals", "SIGTERM/SIGINT preemption requests observed")
_H_SAVE_S = _metrics.histogram(
    "ckpt.save_seconds", "wall seconds per committed checkpoint save "
    "(snapshot + write + validate + commit)")
_H_RESTORE_S = _metrics.histogram(
    "ckpt.restore_seconds", "wall seconds per checkpoint restore")


# --------------------------------------------------------------- preemption

_preempt_lock = threading.Lock()
_preempt = {"requested": False, "signum": None}


def preemption_requested() -> bool:
    return _preempt["requested"]


def request_preemption(signum: Optional[int] = None) -> None:
    """Mark the process as preempted (signal handlers and tests)."""
    with _preempt_lock:
        first = not _preempt["requested"]
        _preempt["requested"] = True
        _preempt["signum"] = signum
    if first:
        _M_PREEMPT.inc()
        _flight.default_recorder().record_event("preempt_signal",
                                                signum=signum)
        logger.warning("preemption requested (signal %s): will checkpoint "
                       "after the in-flight step and exit", signum)


def clear_preemption() -> None:
    with _preempt_lock:
        _preempt["requested"] = False
        _preempt["signum"] = None


# ----------------------------------------------------------------- layout

def step_dir(step: int) -> str:
    return f"step_{int(step):08d}"


def _parse_step(name: str) -> Optional[int]:
    m = _STEP_RE.match(name)
    return int(m.group(1)) if m else None


def all_steps(root: str) -> List[int]:
    """Committed-looking version numbers under `root`, ascending
    (no validation — `.tmp` dirs are never included)."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        s = _parse_step(name)
        if s is not None and os.path.isdir(os.path.join(root, name)):
            out.append(s)
    return sorted(out)


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _write_manifest(path: str, rank: int, step: int,
                    files: List[str]) -> Dict[str, Any]:
    manifest = {"schema": MANIFEST_SCHEMA, "step": int(step),
                "rank": int(rank),
                "files": {name: {"sha256": _sha256(os.path.join(path, name)),
                                 "bytes": os.path.getsize(
                                     os.path.join(path, name))}
                          for name in files}}
    tmp = os.path.join(path, f"manifest_{rank}.json.part")
    with checked_open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, os.path.join(path, f"manifest_{rank}.json"))
    return manifest


def verify_version(path: str, need_sentinel: bool = True) -> Optional[str]:
    """Integrity-check one version directory; returns None when valid,
    else a human-readable reason.  Every file named by every manifest must
    exist with the recorded size and sha256."""
    if not os.path.isdir(path):
        return "missing directory"
    if need_sentinel and not os.path.exists(
            os.path.join(path, COMPLETE_SENTINEL)):
        return "no COMPLETE sentinel (uncommitted or interrupted save)"
    manifests = sorted(f for f in os.listdir(path)
                       if re.match(r"^manifest_\d+\.json$", f))
    if not manifests:
        return "no rank manifests"
    for mf in manifests:
        try:
            with open(os.path.join(path, mf)) as f:
                manifest = json.load(f)
            files = manifest["files"]
        except (OSError, ValueError, KeyError, TypeError) as e:
            return f"unreadable manifest {mf}: {type(e).__name__}"
        for name, want in files.items():
            fp = os.path.join(path, name)
            if not os.path.exists(fp):
                return f"missing file {name}"
            if os.path.getsize(fp) != want["bytes"]:
                return (f"size mismatch for {name}: "
                        f"{os.path.getsize(fp)} != {want['bytes']}")
            if _sha256(fp) != want["sha256"]:
                return f"checksum mismatch for {name}"
    return None


def latest_complete(root: str,
                    before: Optional[int] = None) -> Optional[int]:
    """Newest step under `root` that is committed AND passes integrity
    validation.  Partial (`.tmp`), uncommitted and corrupt versions are
    skipped, counted (`ckpt.skipped_corrupt`) and logged — never loaded."""
    for step in reversed(all_steps(root)):
        if before is not None and step >= before:
            continue
        path = os.path.join(root, step_dir(step))
        reason = verify_version(path)
        if reason is None:
            return step
        kind = "incomplete" if "sentinel" in reason else "corrupt"
        _M_SKIP.inc(reason=kind)
        _flight.default_recorder().record_event(
            "ckpt_skip_corrupt", step=step, reason=reason)
        logger.warning("skipping checkpoint %s: %s", path, reason)
    return None


def commit_single_rank(root: str, step: int,
                       write_files: Callable[[str], List[str]],
                       retries: Optional[int] = None,
                       backoff: Optional[float] = None) -> str:
    """The save/commit protocol for a SINGLE-process auxiliary export
    (the serving prefix-cache persistence — ISSUE 15): ``write_files``
    populates ``step_<N>.tmp`` (routing opens through the
    chaos-injectable ``checked_open``) and returns the file names; this
    helper writes the sha256 manifest, RE-HASHES every file, atomically
    renames the directory and drops the ``COMPLETE`` sentinel — the
    exact commit order the multi-rank checkpoint path uses, so
    :func:`verify_version` / :func:`latest_complete` work unchanged on
    the read side.  Transient OSErrors retry under the checkpoint
    backoff flags.  Returns the committed directory path."""
    from .io_retry import call_with_retries
    if retries is None:
        retries = int(_flags.get_flag("ckpt_io_retries"))
    if backoff is None:
        backoff = float(_flags.get_flag("ckpt_io_backoff_s"))
    os.makedirs(root, exist_ok=True)
    tmp = os.path.join(root, step_dir(step) + ".tmp")
    final = os.path.join(root, step_dir(step))

    def attempt():
        # a retry restarts the version from scratch: partial output
        # from the failed attempt must not survive into the manifest
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        files = list(write_files(tmp))
        _write_manifest(tmp, 0, step, files)

    call_with_retries(attempt, retries=retries, backoff_s=backoff,
                      site=f"export.step_{step}", counter=_M_RETRIES)
    reason = verify_version(tmp, need_sentinel=False)
    if reason is not None:
        raise ValueError(
            f"export validation failed for step {step}: {reason}")

    def do_commit():
        if os.path.isdir(final):
            shutil.rmtree(final)  # stale uncommitted leftover
        os.replace(tmp, final)
        with checked_open(os.path.join(final, COMPLETE_SENTINEL),
                          "w") as f:
            json.dump({"step": int(step), "ranks": 1,
                       "committed_unix": time.time()}, f)

    call_with_retries(do_commit, retries=retries, backoff_s=backoff,
                      site=f"export.commit.step_{step}",
                      counter=_M_RETRIES)
    return final


# ------------------------------------------------------------- tree splits

def _is_array_leaf(v) -> bool:
    import jax
    return isinstance(v, (Tensor, jax.Array, np.ndarray, np.generic))


def _split_tree(state: Dict) -> Tuple[Dict, Dict]:
    """Partition a nested dict into (array leaves, everything else).
    Arrays go through the sharded save path; the rest is pickled by the
    coordinator (`extra_<rank>.pkl`)."""
    arrays: Dict = {}
    extra: Dict = {}
    for k, v in state.items():
        if isinstance(v, dict):
            a, e = _split_tree(v)
            if a:
                arrays[k] = a
            if e:
                extra[k] = e
        elif _is_array_leaf(v):
            arrays[k] = v
        else:
            extra[k] = v
    return arrays, extra


def _deep_merge(a: Dict, b: Dict) -> Dict:
    out = dict(a)
    for k, v in b.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


# ------------------------------------------------------------------ manager

class CheckpointManager:
    """Policy owner for atomic, versioned checkpoints under one root.

    ``save_interval`` paces `maybe_save` (every K optimizer steps);
    ``keep_last`` committed versions survive rotation, plus every version
    whose step is a multiple of ``keep_period`` (0 = no periodic keeps).
    ``async_save=True`` snapshots device state synchronously, then writes
    + commits on a background thread; a failed async save raises on the
    NEXT `save()`/`wait()` call.
    """

    def __init__(self, root: str, save_interval: int = 1,
                 keep_last: int = 2, keep_period: int = 0,
                 async_save: bool = False, coordinator_rank: int = 0):
        if save_interval < 0:
            raise ValueError("save_interval must be >= 0")
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        self.root = str(root)
        self.save_interval = int(save_interval)
        self.keep_last = int(keep_last)
        self.keep_period = int(keep_period)
        self.async_save = bool(async_save)
        self.coordinator_rank = int(coordinator_rank)
        os.makedirs(self.root, exist_ok=True)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._old_handlers: Dict[int, Any] = {}

    # ------------------------------------------------------------ discovery
    def step_path(self, step: int) -> str:
        return os.path.join(self.root, step_dir(step))

    def all_steps(self) -> List[int]:
        return all_steps(self.root)

    def latest_complete(self) -> Optional[int]:
        return latest_complete(self.root)

    # ----------------------------------------------------------------- save
    def wait(self) -> None:
        """Join any in-flight async save; re-raise its failure."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                "async checkpoint save failed; the newest durable "
                "checkpoint is older than you think") from err

    def maybe_save(self, step: int, state, wait: bool = False) -> bool:
        """Save iff `step` is on the save-interval grid.  `state` may be a
        dict or a zero-arg callable returning one (so callers don't build
        the state tree on the steps that won't save)."""
        if self.save_interval <= 0 or step % self.save_interval != 0:
            return False
        if callable(state):
            state = state()
        return self.save(step, state, wait=wait)

    def save(self, step: int, state: Dict, wait: bool = False) -> bool:
        """Snapshot `state` (synchronously) and commit it as version
        `step`.  Returns False when that version is already committed.
        With ``async_save`` the write+commit happens on a background
        thread unless ``wait=True``."""
        self.wait()  # serialize vs the previous async save; surface errors
        if os.path.exists(os.path.join(self.step_path(step),
                                       COMPLETE_SENTINEL)):
            _M_SAVES.inc(result="skipped_existing")
            return False
        t0 = time.perf_counter()
        _flight.default_recorder().record_event("ckpt_save_start", step=step)
        arrays, extra = _split_tree(state)
        # device→host snapshot happens HERE, synchronously: after plan_save
        # returns the caller may donate/overwrite every device buffer
        plan = _sd.plan_save(arrays)
        extra_blob = pickle.dumps(extra) \
            if plan.rank == self.coordinator_rank else None

        if self.async_save and not wait:
            def job():
                try:
                    self._write_version(step, plan, extra_blob, t0)
                except BaseException as e:  # surfaced on the next save()
                    self._error = e
                    _M_SAVES.inc(result="failed")
                    _flight.default_recorder().record_event(
                        "ckpt_save_failed", step=step,
                        error=f"{type(e).__name__}: {e}"[:200])
            self._thread = threading.Thread(
                target=job, name=f"ckpt-save-{step}", daemon=True)
            self._thread.start()
            return True
        try:
            self._write_version(step, plan, extra_blob, t0)
        except BaseException as e:
            _M_SAVES.inc(result="failed")
            _flight.default_recorder().record_event(
                "ckpt_save_failed", step=step,
                error=f"{type(e).__name__}: {e}"[:200])
            raise
        return True

    def _write_version(self, step: int, plan: "_sd.SavePlan",
                       extra_blob: Optional[bytes], t0: float) -> None:
        """One rank's write + (coordinator) validate/commit/rotate, under
        the transient-I/O retry policy."""
        from .io_retry import call_with_retries
        retries = int(_flags.get_flag("ckpt_io_retries"))
        backoff = float(_flags.get_flag("ckpt_io_backoff_s"))
        tmp = self.step_path(step) + ".tmp"
        final = self.step_path(step)
        rank = plan.rank

        def attempt():
            # a retry restarts this rank's files from scratch — partial
            # output from the failed attempt must not survive into the
            # manifest (the tmp dir itself is shared across ranks)
            os.makedirs(tmp, exist_ok=True)
            for name in (plan.data_file, plan.metadata_file,
                         f"extra_{rank}.pkl", f"manifest_{rank}.json"):
                p = os.path.join(tmp, name)
                if os.path.exists(p):
                    os.remove(p)
            written = _sd.write_planned(tmp, plan)
            if extra_blob is not None:
                with checked_open(os.path.join(tmp, f"extra_{rank}.pkl"),
                                  "wb") as f:
                    f.write(extra_blob)
                written.append(f"extra_{rank}.pkl")
            _write_manifest(tmp, rank, step, written)

        call_with_retries(attempt, retries=retries, backoff_s=backoff,
                          site=f"ckpt.save.step_{step}", counter=_M_RETRIES)

        if rank != self.coordinator_rank:
            return
        self._commit(step, tmp, final, retries, backoff)
        _M_SAVES.inc(result="committed")
        _M_BYTES.inc(plan.nbytes)
        dt = time.perf_counter() - t0
        _H_SAVE_S.observe(dt)
        _flight.default_recorder().record_event(
            "ckpt_commit", step=step, bytes=plan.nbytes,
            seconds=round(dt, 4))
        self.rotate(protect=step)

    def _commit(self, step: int, tmp: str, final: str,
                retries: int, backoff: float) -> None:
        """Coordinator: wait for every rank's manifest, validate all
        files, atomically rename, then drop the sentinel."""
        import jax
        from .io_retry import call_with_retries
        n_ranks = jax.process_count()
        deadline = time.monotonic() + float(
            _flags.get_flag("ckpt_commit_timeout_s"))
        while True:
            have = [f for f in os.listdir(tmp)
                    if re.match(r"^manifest_\d+\.json$", f)]
            if len(have) >= n_ranks:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"checkpoint commit for step {step}: only "
                    f"{len(have)}/{n_ranks} rank manifests appeared")
            time.sleep(0.05)
        reason = verify_version(tmp, need_sentinel=False)
        if reason is not None:
            raise ValueError(
                f"checkpoint validation failed for step {step}: {reason}")

        def do_commit():
            if os.path.isdir(final):
                shutil.rmtree(final)  # stale uncommitted leftover
            os.replace(tmp, final)
            with checked_open(os.path.join(final, COMPLETE_SENTINEL),
                              "w") as f:
                json.dump({"step": int(step), "ranks": int(n_ranks),
                           "committed_unix": time.time()}, f)
        call_with_retries(do_commit, retries=retries, backoff_s=backoff,
                          site=f"ckpt.commit.step_{step}",
                          counter=_M_RETRIES)

    # ------------------------------------------------------------- rotation
    def rotate(self, protect: Optional[int] = None) -> List[int]:
        """Delete committed versions beyond ``keep_last``, retaining every
        step that is a multiple of ``keep_period`` (and ``protect``).
        Returns the deleted steps."""
        steps = self.all_steps()
        keep = set(steps[-self.keep_last:])
        if protect is not None:
            keep.add(protect)
        if self.keep_period > 0:
            keep.update(s for s in steps
                        if s > 0 and s % self.keep_period == 0)
        deleted = []
        for s in steps:
            if s in keep:
                continue
            for path in (self.step_path(s), self.step_path(s) + ".tmp"):
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
            deleted.append(s)
            _M_ROTATED.inc()
            _flight.default_recorder().record_event("ckpt_rotate", step=s)
        return deleted

    # ----------------------------------------------------------------- load
    def _resolve(self, step: Optional[int]) -> int:
        if step is None:
            found = self.latest_complete()
            if found is None:
                raise FileNotFoundError(
                    f"no complete checkpoint under {self.root!r}")
            return found
        reason = verify_version(self.step_path(step))
        if reason is not None:
            raise ValueError(
                f"checkpoint step {step} under {self.root!r} is not "
                f"loadable: {reason}")
        return step

    def _load_extra(self, path: str) -> Dict:
        extra: Dict = {}
        for f in sorted(os.listdir(path)):
            if re.match(r"^extra_\d+\.pkl$", f):
                with open(os.path.join(path, f), "rb") as fh:
                    extra = _deep_merge(extra, pickle.load(fh))
        return extra

    def load(self, step: Optional[int] = None) -> Dict:
        """Template-free restore: assemble version `step` (default: the
        newest complete one) into a nested dict — full numpy arrays for
        array leaves, original Python values for the rest."""
        t0 = time.perf_counter()
        step = self._resolve(step)
        path = self.step_path(step)
        out = _deep_merge(read_state_dict(path), self._load_extra(path))
        _H_RESTORE_S.observe(time.perf_counter() - t0)
        return out

    def restore_into(self, state: Dict, step: Optional[int] = None,
                     resize_trailing: bool = False) -> Tuple[Dict, Dict]:
        """Sharded in-place restore: every array leaf of `state` (Tensor,
        jax.Array or numpy) is reloaded with resharding preserved (target
        sharding wins, `load_state_dict` semantics).  Returns
        ``(arrays, extra)`` where `arrays` mirrors the array leaves of
        `state` with the loaded values and `extra` holds the non-array
        leaves of the checkpoint.

        ``resize_trailing=True`` lets a leaf's LAST dim differ from the
        saved shape (truncate / zero-fill) — the elastic-ZeRO world-size
        re-plan, where flat (Fp,) shards change only their dp-dependent
        pad (`load_state_dict` docs)."""
        import jax.numpy as jnp
        t0 = time.perf_counter()
        step = self._resolve(step)
        path = self.step_path(step)
        arrays, _ = _split_tree(state)

        def wrap(node):
            if isinstance(node, dict):
                return {k: wrap(v) for k, v in node.items()}
            if isinstance(node, Tensor):
                return node
            return Tensor._wrap(jnp.asarray(node))
        wrapped = wrap(arrays)
        load_state_dict(wrapped, path, resize_trailing=resize_trailing)

        def unwrap(node):
            if isinstance(node, dict):
                return {k: unwrap(v) for k, v in node.items()}
            return node._value
        out = unwrap(wrapped)
        extra = self._load_extra(path)
        _H_RESTORE_S.observe(time.perf_counter() - t0)
        return out, extra

    # ------------------------------------------------------------ preemption
    @property
    def preempted(self) -> bool:
        return preemption_requested()

    def install_signal_handlers(self, signals=(
            _signal.SIGTERM, _signal.SIGINT)) -> None:
        """SIGTERM/SIGINT set the preemption flag instead of killing the
        process; the training loop checks `preempted` after each step,
        saves, and exits cleanly.  Restore with
        `uninstall_signal_handlers` (fit does both)."""
        for sig in signals:
            if sig in self._old_handlers:
                continue
            try:
                self._old_handlers[sig] = _signal.signal(
                    sig, lambda signum, frame: request_preemption(signum))
            except ValueError:
                # not the main thread: the caller keeps the default
                # handlers and can still request_preemption() manually
                logger.warning("cannot install signal handlers off the "
                               "main thread; preemption flag only")
                break

    def uninstall_signal_handlers(self) -> None:
        for sig, old in self._old_handlers.items():
            _signal.signal(sig, old)
        self._old_handlers.clear()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> bool:
        try:
            self.wait()
        finally:
            self.uninstall_signal_handlers()
        return False
