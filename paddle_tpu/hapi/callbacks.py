"""Training callbacks for the high-level Model API.

Parity: `python/paddle/hapi/callbacks.py` — Callback (`:131`), CallbackList
(`:71`), ProgBarLogger (`:300`), ModelCheckpoint (`:550`), LRScheduler
(`:619`), EarlyStopping (`:719`), ReduceLROnPlateau (`:1172`).
"""

from __future__ import annotations

import numbers
import os
from typing import List, Optional

import numpy as np

from .progressbar import ProgressBar

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau"]


class Callback:
    """Base class; hook methods receive a `logs` dict."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    # train/eval/predict lifecycle -----------------------------------------
    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass
    def on_predict_batch_begin(self, step, logs=None): pass
    def on_predict_batch_end(self, step, logs=None): pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks):
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or ["loss"]})
    return cl


class ProgBarLogger(Callback):
    """Prints loss + metrics every `log_freq` steps.  Parity: `:300`."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")
        self.train_progbar = ProgressBar(num=self.steps,
                                         verbose=self.verbose)
        self.train_step = 0

    def _logs_values(self, logs):
        return {k: v for k, v in logs.items()
                if isinstance(v, (numbers.Number, list, tuple, np.ndarray))}

    def on_train_batch_end(self, step, logs=None):
        self.train_step += 1
        if self.verbose and self.train_step % self.log_freq == 0:
            self.train_progbar.update(self.train_step,
                                      self._logs_values(logs or {}))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            self.train_progbar.update(self.train_step,
                                      self._logs_values(logs or {}))

    def on_eval_begin(self, logs=None):
        n = (logs or {}).get("steps")
        self.eval_progbar = ProgressBar(num=n, verbose=self.verbose)
        self.eval_step = 0
        if self.verbose:
            print("Eval begin...")

    def on_eval_batch_end(self, step, logs=None):
        self.eval_step += 1
        if self.verbose and self.eval_step % self.log_freq == 0:
            self.eval_progbar.update(self.eval_step,
                                     self._logs_values(logs or {}))

    def on_eval_end(self, logs=None):
        if self.verbose:
            self.eval_progbar.update(self.eval_step,
                                     self._logs_values(logs or {}))
            print("Eval samples done")


class ModelCheckpoint(Callback):
    """Saves `{save_dir}/{epoch}` every save_freq epochs and `final`.
    Parity: `:550`."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and \
                epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler.  Parity: `:619`."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_lr", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    """Stop when `monitor` stops improving.  Parity: `:719`."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best_value = -np.inf if mode == "max" else np.inf

    def _improved(self, cur):
        if self.mode == "max":
            return cur > self.best_value + self.min_delta
        return cur < self.best_value - self.min_delta

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = logs[self.monitor]
        if isinstance(cur, (list, tuple, np.ndarray)):
            cur = float(np.asarray(cur).reshape(-1)[0])
        if self._improved(cur):
            self.best_value = cur
            self.wait_epoch = 0
            if self.save_best_model and getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir,
                                             "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            if self.verbose:
                print(f"Early stopping: {self.monitor} did not improve for "
                      f"{self.patience + 1} evals "
                      f"(best {self.best_value:.5f})")


class ReduceLROnPlateau(Callback):
    """Multiply LR by `factor` when `monitor` plateaus.  Parity: `:1172`."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self._reset()

    def _reset(self):
        self.best = -np.inf if self.mode == "max" else np.inf
        self.wait = 0
        self.cooldown_counter = 0

    def _improved(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = logs[self.monitor]
        if isinstance(cur, (list, tuple, np.ndarray)):
            cur = float(np.asarray(cur).reshape(-1)[0])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._improved(cur):
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                from ..optimizer.lr import LRScheduler as Sched
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    if isinstance(getattr(opt, "_lr", None), Sched):
                        import warnings
                        warnings.warn(
                            "ReduceLROnPlateau: optimizer uses an "
                            "LRScheduler; cannot override its LR — skipping")
                    else:
                        old = opt.get_lr()
                        new = max(old * self.factor, self.min_lr)
                        if old - new > 1e-12:
                            opt.set_lr(new)
                            if self.verbose:
                                print(f"ReduceLROnPlateau: lr {old:.2e} -> "
                                      f"{new:.2e}")
                self.cooldown_counter = self.cooldown
                self.wait = 0
