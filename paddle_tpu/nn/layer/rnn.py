"""Recurrent layers. Parity: `python/paddle/nn/layer/rnn.py`.

TPU-native design: the time loop is `jax.lax.scan` (compiles to one fused XLA
while loop; no per-step dispatch), batch-major [B, T, *] like paddle's
time_major=False default."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...ops.registry import dispatch as _d, register_op
from .. import initializer as I
from .layers import Layer

__all__ = ["SimpleRNN", "LSTM", "GRU", "SimpleRNNCell", "LSTMCell", "GRUCell",
           "RNN"]


def _rnn_scan_impl(x, h0, c0, params, *, mode, num_layers, bidirect, time_major):
    """params: flat list per (layer, direction): [w_ih, w_hh, b_ih, b_hh]."""
    if time_major:
        x = jnp.swapaxes(x, 0, 1)  # -> [B, T, I]

    def cell_step(mode, w_ih, w_hh, b_ih, b_hh, h, c, xt):
        gates = xt @ w_ih.T + h @ w_hh.T + b_ih + b_hh
        if mode == "LSTM":
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        if mode == "GRU":
            r, z, n = jnp.split(gates, 3, axis=-1)
            # paddle/cudnn GRU: n = tanh(x W_n + r * (h U_n + b_hn))
            xr = xt @ w_ih.T + b_ih
            hr = h @ w_hh.T + b_hh
            xr_r, xr_z, xr_n = jnp.split(xr, 3, axis=-1)
            hr_r, hr_z, hr_n = jnp.split(hr, 3, axis=-1)
            r = jax.nn.sigmoid(xr_r + hr_r)
            z = jax.nn.sigmoid(xr_z + hr_z)
            n = jnp.tanh(xr_n + r * hr_n)
            h_new = (1 - z) * n + z * h
            return h_new, c
        h_new = jnp.tanh(gates)
        return h_new, c

    num_dirs = 2 if bidirect else 1
    out = x
    h_finals, c_finals = [], []
    for layer in range(num_layers):
        dir_outs = []
        for d in range(num_dirs):
            pi = (layer * num_dirs + d) * 4
            w_ih, w_hh, b_ih, b_hh = params[pi:pi + 4]
            idx = layer * num_dirs + d
            h_init = h0[idx]
            c_init = c0[idx] if c0 is not None else jnp.zeros_like(h_init)
            seq = out if d == 0 else jnp.flip(out, axis=1)
            xs = jnp.swapaxes(seq, 0, 1)  # [T, B, I] for scan

            def step(carry, xt, _w_ih=w_ih, _w_hh=w_hh, _b_ih=b_ih,
                     _b_hh=b_hh):
                h, c = carry
                h2, c2 = cell_step(mode, _w_ih, _w_hh, _b_ih, _b_hh, h, c, xt)
                return (h2, c2), h2

            (hf, cf), ys = jax.lax.scan(step, (h_init, c_init), xs)
            ys = jnp.swapaxes(ys, 0, 1)  # [B, T, H]
            if d == 1:
                ys = jnp.flip(ys, axis=1)
            dir_outs.append(ys)
            h_finals.append(hf)
            c_finals.append(cf)
        out = dir_outs[0] if num_dirs == 1 else jnp.concatenate(dir_outs, -1)
    h_out = jnp.stack(h_finals, axis=0)
    c_out = jnp.stack(c_finals, axis=0)
    if time_major:
        out = jnp.swapaxes(out, 0, 1)
    if mode == "LSTM":
        return out, h_out, c_out
    return out, h_out


register_op("rnn_scan", _rnn_scan_impl)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirect = direction in ("bidirect", "bidirectional")
        self.time_major = time_major
        self.dropout = dropout
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1}[mode]
        num_dirs = 2 if self.bidirect else 1
        self._param_names = []
        std = 1.0 / np.sqrt(hidden_size)
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_size = input_size if layer == 0 else hidden_size * num_dirs
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                names = [f"weight_ih{sfx}", f"weight_hh{sfx}",
                         f"bias_ih{sfx}", f"bias_hh{sfx}"]
                shapes = [[gate_mult * hidden_size, in_size],
                          [gate_mult * hidden_size, hidden_size],
                          [gate_mult * hidden_size], [gate_mult * hidden_size]]
                for n, s in zip(names, shapes):
                    p = self.create_parameter(
                        s, default_initializer=I.Uniform(-std, std))
                    self.add_parameter(n, p)
                self._param_names.append(names)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch_axis = 1 if self.time_major else 0
        b = inputs.shape[batch_axis]
        num_dirs = 2 if self.bidirect else 1
        n_states = self.num_layers * num_dirs
        if initial_states is None:
            from ...ops.creation import zeros
            h0 = zeros([n_states, b, self.hidden_size], dtype=inputs.dtype)
            c0 = zeros([n_states, b, self.hidden_size], dtype=inputs.dtype) \
                if self.mode == "LSTM" else None
        else:
            if self.mode == "LSTM":
                h0, c0 = initial_states
            else:
                h0, c0 = initial_states, None
        params = []
        for names in self._param_names:
            params.extend(getattr(self, n) for n in names)
        res = _d("rnn_scan", (inputs, h0, c0, params),
                 {"mode": self.mode, "num_layers": self.num_layers,
                  "bidirect": self.bidirect, "time_major": self.time_major})
        if self.mode == "LSTM":
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__("RNN_TANH", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class _CellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...ops.creation import full
        b = batch_ref.shape[batch_dim_idx]
        return full([b, self.hidden_size], init_value,
                    dtype=dtype or "float32")


class SimpleRNNCell(_CellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", **kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [hidden_size], is_bias=True, default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [hidden_size], is_bias=True, default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        from ...ops import linalg, math as _math
        if states is None:
            states = self.get_initial_states(inputs)
        h = linalg.matmul(inputs, self.weight_ih, transpose_y=True) + \
            linalg.matmul(states, self.weight_hh, transpose_y=True) + \
            self.bias_ih + self.bias_hh
        h = _math.tanh(h)
        return h, h


class LSTMCell(_CellBase):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size],
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size],
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states
        res = _d("lstm_cell", (inputs, h, c, self.weight_ih, self.weight_hh,
                               self.bias_ih, self.bias_hh), {})
        h2, c2 = res
        return h2, (h2, c2)


def _lstm_cell_impl(x, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


register_op("lstm_cell", _lstm_cell_impl)


class GRUCell(_CellBase):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size],
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size],
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        res = _d("gru_cell", (inputs, states, self.weight_ih, self.weight_hh,
                              self.bias_ih, self.bias_hh), {})
        return res, res


def _gru_cell_impl(x, h, w_ih, w_hh, b_ih, b_hh):
    xr = x @ w_ih.T + b_ih
    hr = h @ w_hh.T + b_hh
    xr_r, xr_z, xr_n = jnp.split(xr, 3, axis=-1)
    hr_r, hr_z, hr_n = jnp.split(hr, 3, axis=-1)
    r = jax.nn.sigmoid(xr_r + hr_r)
    z = jax.nn.sigmoid(xr_z + hr_z)
    n = jnp.tanh(xr_n + r * hr_n)
    return (1 - z) * n + z * h


register_op("gru_cell", _gru_cell_impl)


class RNN(Layer):
    """Wraps a cell into a scan over time (paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # eager python loop (jit capture unrolls; fine for small T)
        from ...ops import manipulation as _m
        t_axis = 0 if self.time_major else 1
        steps = inputs.shape[t_axis]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        states = initial_states
        outs = []
        for t in order:
            xt = _m.squeeze(_m.slice(inputs, [t_axis], [t], [t + 1]), t_axis)
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        out = _m.stack(outs, axis=t_axis)
        return out, states
