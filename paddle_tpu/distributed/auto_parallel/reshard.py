"""Reshard engine: placement-transition registry with Partial semantics.

Parity: `paddle/phi/core/distributed/auto_parallel/reshard/` —
s_to_r_reshard_function.cc (all-gather), r_to_s (slice), p_to_r
(all-reduce), p_to_s (reduce-scatter), s_to_s (all-to-all),
same_status / cross-mesh (send-recv), and the registry in
reshard_function_registry.cc.

TPU-native: a pending-sum ("Partial") value is represented explicitly as a
jax array with a leading unreduced axis of length `mesh_dim_size`, sharded
over that mesh dim — the canonical unreduced layout.  Transitions out of
Partial are a `sum` over that axis with the target sharding constrained;
XLA lowers exactly to the all-reduce (p2r) / reduce-scatter (p2s) the
reference codes by hand.  Shard<->Shard and Shard<->Replicate transitions
are sharding moves (device_put / with_sharding_constraint) that GSPMD
lowers to all-to-all / all-gather / slice.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.jax_compat import shard_map as _compat_shard_map
from ...framework.tensor import Tensor
from .placement import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh

__all__ = ["PartialTensor", "reshard_partial", "make_partial",
           "register_reshard", "get_reshard_fn"]


_RESHARD: Dict[Tuple[str, str], Callable] = {}


def _kind(p: Placement) -> str:
    if p.is_partial():
        return "p"
    if p.is_shard():
        return "s"
    return "r"


def register_reshard(src: str, dst: str):
    def deco(fn):
        _RESHARD[(src, dst)] = fn
        return fn
    return deco


def get_reshard_fn(src: Placement, dst: Placement) -> Callable:
    key = (_kind(src), _kind(dst))
    if key not in _RESHARD:
        raise NotImplementedError(f"no reshard rule {key[0]}->{key[1]}")
    return _RESHARD[key]


class PartialTensor:
    """A pending-sum DistTensor along one mesh dim.

    `unreduced` has shape (mesh_dim_size, *logical_shape) and is sharded on
    dim 0 over `axis_name` — shard i holds rank i's partial contribution.
    """

    def __init__(self, unreduced: jax.Array, mesh: Mesh, axis_name: str):
        self.unreduced = unreduced
        self.mesh = mesh
        self.axis_name = axis_name

    @property
    def logical_shape(self):
        return tuple(self.unreduced.shape[1:])


def make_partial(fn_per_rank, mesh: Mesh, axis_name: str, *args,
                 in_specs=None) -> PartialTensor:
    """Build a PartialTensor by running `fn_per_rank(local_slices...)`
    under shard_map.  `in_specs` gives each arg's PartitionSpec (default:
    sharded on its leading dim) — a row-parallel matmul needs
    in_specs=(P(None, axis), P(axis, None))."""
    import functools

    if in_specs is None:
        in_specs = tuple(P(axis_name) for _ in args)
    else:
        in_specs = tuple(in_specs)

    @functools.partial(_compat_shard_map, mesh=mesh, in_specs=in_specs,
                       out_specs=P(axis_name))
    def run(*local_args):
        out = fn_per_rank(*local_args)
        return out[None]  # leading unreduced axis

    return PartialTensor(run(*args), mesh, axis_name)


def _move(val, sharding):
    if isinstance(val, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(val, sharding)
    return jax.device_put(val, sharding)


# ------------------------------------------------------------- transitions
@register_reshard("p", "r")
def p_to_r(pt: PartialTensor, dst: Placement, **kw):
    """Pending sum -> replicated: one all-reduce (`p_to_r_reshard...cc`)."""
    out = jnp.sum(pt.unreduced, axis=0)
    repl = NamedSharding(pt.mesh, P(*([None] * out.ndim)))
    return _move(out, repl)


@register_reshard("p", "s")
def p_to_s(pt: PartialTensor, dst: Shard, **kw):
    """Pending sum -> sharded: reduce-scatter (`p_to_s_reshard...cc`)."""
    out = jnp.sum(pt.unreduced, axis=0)
    entries = [None] * out.ndim
    entries[dst.get_dim()] = pt.axis_name
    return _move(out, NamedSharding(pt.mesh, P(*entries)))


@register_reshard("s", "r")
def s_to_r(val, dst: Placement, mesh=None, axis_name=None, **kw):
    """Sharded -> replicated: all-gather (`s_to_r_reshard...cc`)."""
    return _move(val, NamedSharding(mesh, P(*([None] * val.ndim))))


@register_reshard("r", "s")
def r_to_s(val, dst: Shard, mesh=None, axis_name=None, **kw):
    """Replicated -> sharded: local slice (`r_to_s_reshard...cc`)."""
    entries = [None] * val.ndim
    entries[dst.get_dim()] = axis_name
    return _move(val, NamedSharding(mesh, P(*entries)))


@register_reshard("s", "s")
def s_to_s(val, dst: Shard, mesh=None, axis_name=None, src_dim=None, **kw):
    """Shard(i) -> Shard(j): all-to-all (`s_to_s_reshard...cc`)."""
    entries = [None] * val.ndim
    entries[dst.get_dim()] = axis_name
    return _move(val, NamedSharding(mesh, P(*entries)))


def reshard_partial(pt: PartialTensor, dst: Placement) -> Tensor:
    """Materialize a PartialTensor under the destination placement."""
    fn = get_reshard_fn(Partial(), dst)
    return Tensor._wrap(fn(pt, dst))


@register_reshard("r", "p")
def r_to_p(val, dst: Placement, mesh=None, axis_name=None, **kw):
    """Replicated -> pending-sum: rank 0 of the axis keeps the value,
    every other rank holds zeros, so a later p->r restores the original
    (`r_to_p_reshard_function.cc` semantics).  The unreduced stack is
    laid out dim-0-sharded over the axis (PartialTensor's contract: one
    slice per rank, not n replicated copies)."""
    n = mesh.shape[axis_name]
    tiles = jnp.stack([val] + [jnp.zeros_like(val)] * (n - 1))
    tiles = _move(tiles, NamedSharding(
        mesh, P(axis_name, *([None] * val.ndim))))
    return PartialTensor(tiles, mesh, axis_name)


def nd_mesh_reshard(value, mesh, src_placements, dst_placements,
                    mesh_dim_names=None):
    """Reshard over an N-D mesh by decomposing into per-axis pairwise
    steps (`nd_mesh_reshard_function.cc`: SetVirtualMeshDim + one 1-D
    reshard per changed axis).

    value: jax array laid out per `src_placements` (one Placement per
    mesh axis).  Returns the array laid out per `dst_placements`.
    Partial placements are handled first (p->r / p->s on their axis),
    then shard/replicate changes axis by axis — the same ordering the
    reference uses so intermediate layouts stay materializable."""
    names = list(mesh_dim_names or mesh.axis_names)
    assert len(src_placements) == len(names) == len(dst_placements)

    def spec_of(placements):
        entries = [None] * value.ndim
        for ax_name, p in zip(names, placements):
            if _kind(p) == "s":
                d = p.get_dim()
                if entries[d] is None:
                    entries[d] = ax_name
                elif isinstance(entries[d], tuple):
                    entries[d] = entries[d] + (ax_name,)
                else:
                    entries[d] = (entries[d], ax_name)
        return P(*entries)

    cur = list(src_placements)
    # phase 1: resolve partials (their axis must reduce before any
    # shard-dim juggling references the true values)
    for i, (s, d) in enumerate(zip(list(cur), dst_placements)):
        if _kind(s) == "p" and _kind(d) != "p":
            psum_axis = names[i]
            # value carries an unreduced leading stack only inside
            # PartialTensor flows; at the jax-array level a partial axis
            # means "sum over replicas of that axis" — express it as a
            # shard_map psum over the axis
            in_spec = spec_of(cur)
            mid = list(cur)
            mid[i] = Replicate()
            out_spec = spec_of(mid)
            value = jax.jit(_compat_shard_map(
                lambda x: jax.lax.psum(x, psum_axis), mesh=mesh,
                in_specs=in_spec, out_specs=out_spec,
                check_vma=False))(value)
            cur = mid
    # phase 2: one GSPMD relayout per remaining changed axis
    for i, d in enumerate(dst_placements):
        if _kind(cur[i]) == _kind(d) and (
                _kind(d) != "s" or cur[i].get_dim() == d.get_dim()):
            continue
        if _kind(d) == "p":
            raise NotImplementedError(
                "nd reshard to a Partial placement (x->p) is not a "
                "materializable layout; reshard to r or s instead")
        step = list(cur)
        step[i] = d
        value = _move(value, NamedSharding(mesh, spec_of(step)))
        cur = step
    return value
