"""Distribution base class.

Parity: `python/paddle/distribution/distribution.py` (Distribution:
sample/rsample/prob/log_prob/entropy/cdf, batch_shape/event_shape).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

import paddle_tpu as paddle
from ..framework.tensor import Tensor

__all__ = ["Distribution"]


def _t(x, dtype="float32") -> Tensor:
    if isinstance(x, Tensor):
        return x
    return paddle.to_tensor(np.asarray(x, dtype))


class Distribution:
    def __init__(self, batch_shape: Sequence[int] = (),
                 event_shape: Sequence[int] = ()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self) -> Tuple[int, ...]:
        return self._batch_shape

    @property
    def event_shape(self) -> Tuple[int, ...]:
        return self._event_shape

    @property
    def mean(self) -> Tensor:
        raise NotImplementedError

    @property
    def variance(self) -> Tensor:
        raise NotImplementedError

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        """Draw (non-reparameterized) samples of `shape` + batch + event."""
        with paddle.no_grad():
            return self.rsample(shape)

    def rsample(self, shape: Sequence[int] = ()) -> Tensor:
        raise NotImplementedError

    def log_prob(self, value) -> Tensor:
        raise NotImplementedError

    def prob(self, value) -> Tensor:
        return paddle.exp(self.log_prob(value))

    def entropy(self) -> Tensor:
        raise NotImplementedError

    def cdf(self, value) -> Tensor:
        raise NotImplementedError

    def kl_divergence(self, other: "Distribution") -> Tensor:
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape: Sequence[int]) -> Tuple[int, ...]:
        return tuple(sample_shape) + self._batch_shape + self._event_shape
