"""Tensor creation ops. Parity: `python/paddle/tensor/creation.py`."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import dtypes as _dtypes
from ..framework.tensor import Tensor, to_tensor
from .registry import dispatch as _d, register_op
from ..core.dtypes import canonical_index_dtype as _ityfn
_ITYPE = _ityfn()

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "diag", "diagflat", "tril", "triu", "meshgrid", "assign", "clone",
    "create_parameter", "tril_indices", "triu_indices", "complex_",
    "real", "imag", "conj", "angle",
]


def _dt(dtype):
    return _dtypes.convert_dtype(dtype) if dtype is not None else \
        _dtypes.get_default_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        shape = [shape]
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor._wrap(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor._wrap(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None and isinstance(fill_value, bool):
        return Tensor._wrap(jnp.full(_shape(shape), fill_value))
    return Tensor._wrap(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype)


register_op("zeros_like", lambda x: jnp.zeros_like(x))
register_op("ones_like", lambda x: jnp.ones_like(x))


def zeros_like(x, dtype=None, name=None) -> Tensor:
    out = Tensor._wrap(jnp.zeros_like(x._value if isinstance(x, Tensor) else x))
    return out.astype(dtype) if dtype is not None else out


def ones_like(x, dtype=None, name=None) -> Tensor:
    out = Tensor._wrap(jnp.ones_like(x._value if isinstance(x, Tensor) else x))
    return out.astype(dtype) if dtype is not None else out


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    d = _dtypes.convert_dtype(dtype) if dtype is not None else v.dtype
    return Tensor._wrap(jnp.full(v.shape, fill_value, d))


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    def _scalar(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = _scalar(start), _scalar(end), _scalar(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dtype = _ITYPE
        else:
            dtype = _dtypes.get_default_dtype()
    return Tensor._wrap(jnp.arange(start, end, step, _dtypes.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    def _scalar(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor._wrap(jnp.linspace(_scalar(start), _scalar(stop), int(_scalar(num)),
                                     dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    return Tensor._wrap(jnp.logspace(start, stop, int(num), base=base,
                                     dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor._wrap(jnp.eye(int(num_rows),
                                int(num_columns) if num_columns else None,
                                dtype=_dt(dtype)))


register_op("diag", lambda x, *, offset: jnp.diag(x, k=offset))
register_op("diagflat", lambda x, *, offset: jnp.diagflat(x, k=offset))
register_op("tril", lambda x, *, diagonal: jnp.tril(x, k=diagonal))
register_op("triu", lambda x, *, diagonal: jnp.triu(x, k=diagonal))


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    out = _d("diag", (x,), {"offset": int(offset)})
    return out


def diagflat(x, offset=0, name=None) -> Tensor:
    return _d("diagflat", (x,), {"offset": int(offset)})


def tril(x, diagonal=0, name=None) -> Tensor:
    return _d("tril", (x,), {"diagonal": int(diagonal)})


def triu(x, diagonal=0, name=None) -> Tensor:
    return _d("triu", (x,), {"diagonal": int(diagonal)})


def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = np.tril_indices(row, offset, col)
    return Tensor._wrap(jnp.asarray(np.stack([r, c]), _dtypes.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor._wrap(jnp.asarray(np.stack([r, c]), _dtypes.convert_dtype(dtype)))


def meshgrid(*args, name=None):
    args = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    return [Tensor._wrap(v) for v in jnp.meshgrid(*vals, indexing="ij")]


register_op("assign", lambda x: x + 0 if hasattr(x, "dtype") else jnp.asarray(x))


def assign(x, output=None, name=None) -> Tensor:
    if not isinstance(x, Tensor):
        x = Tensor(np.asarray(x))
    out = _d("assign", (x,), {})
    if output is not None:
        output.set_value(out._value)
        return output
    return out


def clone(x, name=None) -> Tensor:
    return assign(x)


def complex_(real, imag, name=None) -> Tensor:
    return _d("complex", (real, imag), {})


register_op("real", lambda x: jnp.real(x))
register_op("imag", lambda x: jnp.imag(x))
register_op("conj", lambda x: jnp.conj(x))
register_op("angle", lambda x: jnp.angle(x))


def real(x, name=None) -> Tensor:
    """paddle.real (`tensor/attribute.py` real)."""
    return _d("real", (x,), {})


def imag(x, name=None) -> Tensor:
    """paddle.imag (`tensor/attribute.py` imag)."""
    return _d("imag", (x,), {})


def conj(x, name=None) -> Tensor:
    """paddle.conj (`tensor/math.py` conj)."""
    return _d("conj", (x,), {})


def angle(x, name=None) -> Tensor:
    """paddle.angle (`tensor/math.py` angle)."""
    return _d("angle", (x,), {})


def create_parameter(shape, dtype=None, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """paddle.create_parameter equivalent (base/param_attr path)."""
    from ..framework.tensor import Parameter
    from ..nn import initializer as I
    shape = _shape(shape)
    init = default_initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    p = Parameter(jnp.zeros(shape, _dt(dtype)), name=name)
    init(p)
    return p


# the "complex" registry op comes from the YAML single source
# (ops/specs/ops.yaml `complex`); `complex_` above dispatches to it
