"""Comm watchdog + cross-rank sanity checks.

Parity targets:
- `paddle/phi/core/distributed/comm_task_manager.h:37` CommTaskManager — a
  background thread that tracks every collective task's start/end, flags
  hangs past a timeout, and keeps error traces for post-mortems.
- `paddle/phi/core/distributed/check/static_check.h:24` CommStaticCheck —
  same shape/dtype/place across ranks before a collective runs.
- `check/nccl_dynamic_check.h` NCCLDynamicCheck — runtime meta broadcast.

TPU-native redesign: compiled SPMD collectives cannot hang rank-subsets the
way NCCL rings can (XLA schedules them; a lost chip surfaces as a PJRT
execute error), so the watchdog guards the HOST control plane instead — the
TCPStore barriers, eager p2p waits and rendezvous where multi-host jobs
actually wedge.  Tasks are registered around every store wait; a daemon
thread scans for overdue tasks, reports which peer is missing (via store
heartbeats), and records traces.  Meta checks ride the p2p payload
(sender packs shape/dtype; receiver verifies) and a store round for
collectives when FLAGS_comm_static_check is on.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .. import flags as _flags

__all__ = ["CommTaskManager", "comm_task", "static_check_meta",
           "Heartbeat", "dead_peers"]

# The watchdog flags are registered in flags.py (single source of truth) so
# collective.py's readers never depend on this module's import having run.


@dataclass
class CommTask:
    task_id: int
    name: str
    meta: Dict[str, Any]
    started: float = field(default_factory=time.monotonic)
    stack: str = ""
    done: bool = False
    error: Optional[str] = None


class CommTaskManager:
    """Tracks host comm tasks; a daemon scan thread reports hangs.

    Singleton like the reference's (`comm_task_manager.cc`); cheap enough
    to always be on — registration is two dict ops, the scan thread wakes
    once a second only while tasks are live.
    """

    _instance: Optional["CommTaskManager"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._tasks: Dict[int, CommTask] = {}
        self._history: List[CommTask] = []
        self._next_id = 0
        self._tlock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._hang_hooks: List[Any] = []
        self._reported: set = set()

    @classmethod
    def instance(cls) -> "CommTaskManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # ------------------------------------------------------------- tasks
    def start_task(self, name: str, **meta) -> int:
        if not _flags.get_flag("enable_comm_watchdog"):
            return -1
        with self._tlock:
            tid = self._next_id
            self._next_id += 1
            task = CommTask(tid, name, meta,
                            stack="".join(traceback.format_stack(limit=8)))
            self._tasks[tid] = task
            self._ensure_thread_locked()
        return tid

    def end_task(self, tid: int, error: Optional[str] = None):
        if tid < 0:
            return
        with self._tlock:
            task = self._tasks.pop(tid, None)
            if task is not None:
                task.done = True
                task.error = error
                self._history.append(task)
                del self._history[:-64]  # bounded post-mortem buffer

    def live_tasks(self) -> List[CommTask]:
        with self._tlock:
            return list(self._tasks.values())

    def history(self) -> List[CommTask]:
        with self._tlock:
            return list(self._history)

    def add_hang_hook(self, fn):
        """fn(task) called once per task when it exceeds the timeout."""
        self._hang_hooks.append(fn)

    # -------------------------------------------------------------- scan
    def _ensure_thread_locked(self):
        """Caller holds _tlock.  The scan loop hands its slot back (sets
        _thread=None) under the same lock before exiting, so either the
        loop saw this task, or we see a dead/None thread and start one —
        a task can never be left unmonitored."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._scan_loop,
                                            name="comm-watchdog",
                                            daemon=True)
            self._thread.start()

    def _scan_loop(self):
        while not self._stop.wait(1.0):
            timeout = float(_flags.get_flag("comm_watchdog_timeout_s"))
            now = time.monotonic()
            with self._tlock:
                if not self._tasks:
                    self._thread = None  # idle: restartable by start_task
                    break
                overdue = [t for t in self._tasks.values()
                           if now - t.started > timeout
                           and t.task_id not in self._reported]
                for t in overdue:
                    self._reported.add(t.task_id)
            for t in overdue:
                self._report_hang(t)

    def _report_hang(self, task: CommTask):
        import logging
        missing = ""
        store = task.meta.get("store")
        if store is not None:
            dead = dead_peers(store, task.meta.get("world_size", 0),
                              task.meta.get("generation", "0"))
            if dead:
                missing = f"; ranks without heartbeat: {dead}"
        msg = (f"[comm watchdog] task '{task.name}' "
               f"(meta={ {k: v for k, v in task.meta.items() if k != 'store'} }) "
               f"has been blocked for "
               f"{time.monotonic() - task.started:.0f}s{missing}\n"
               f"started at:\n{task.stack}")
        logging.getLogger("paddle_tpu.distributed").error(msg)
        for fn in self._hang_hooks:
            try:
                fn(task)
            except Exception:
                pass

    def shutdown(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


class comm_task:
    """Context manager registering a host comm task with the watchdog."""

    def __init__(self, name: str, **meta):
        self._name = name
        self._meta = meta
        self._tid = -1

    def __enter__(self):
        self._tid = CommTaskManager.instance().start_task(
            self._name, **self._meta)
        return self

    def __exit__(self, exc_type, exc, tb):
        CommTaskManager.instance().end_task(
            self._tid, error=repr(exc) if exc is not None else None)
        return False


# --------------------------------------------------------------------------
# Heartbeats: liveness through the launcher's store so a hang report can say
# WHICH rank is missing (reference: TCPStore barrier keys + Watcher polling).
# --------------------------------------------------------------------------

class Heartbeat:
    """Publishes this rank's liveness to the store every `interval` s.

    The published value is a monotonically increasing sequence number, NOT
    a wall-clock timestamp — liveness is judged by whether the counter
    advances, so cross-host clock skew can't produce false dead reports.
    """

    def __init__(self, store, rank: int, generation: str = "0",
                 interval: float = 5.0):
        self._store = store
        self._rank = rank
        self._generation = generation
        self._interval = interval
        self._seq = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"heartbeat-r{rank}")

    def key(self) -> str:
        return f"__hb__/{self._generation}/{self._rank}"

    def start(self):
        self.beat()
        self._thread.start()
        return self

    def beat(self):
        self._seq += 1
        self._store.set(self.key(), str(self._seq).encode())

    def _loop(self):
        failures = 0
        while not self._stop.wait(self._interval):
            try:
                self.beat()
                failures = 0
            except Exception:
                # a store outage must not permanently kill a live rank's
                # heartbeat (later hang reports would name THIS rank dead):
                # back off — capped at 8x the interval — and keep retrying
                # for as long as the rank lives; the beat resumes the
                # moment the store does
                failures += 1
                extra = self._interval * min(2 ** min(failures, 3) - 1, 8)
                if self._stop.wait(extra):
                    return

    def stop(self):
        self._stop.set()


def _read_heartbeats(store, world_size: int, generation: str):
    seqs = {}
    for r in range(world_size):
        key = f"__hb__/{generation}/{r}"
        try:
            if store.check(key):
                seqs[r] = int(store.get(key).decode())
        except Exception:
            pass
    return seqs


def dead_peers(store, world_size: int, generation: str = "0",
               probe: float = 12.0) -> List[int]:
    """Ranks with no heartbeat key, or whose counter does not advance
    within `probe` seconds (> 2x the default beat interval).  Blocking is
    fine: this runs from hang reports, after minutes of stall."""
    before = _read_heartbeats(store, world_size, generation)
    missing = [r for r in range(world_size) if r not in before]
    if len(missing) == world_size:
        return missing  # nobody ever beat: don't stall the report
    time.sleep(probe)
    after = _read_heartbeats(store, world_size, generation)
    return [r for r in range(world_size)
            if r not in after or after[r] <= before.get(r, -1)]


# --------------------------------------------------------------------------
# Cross-rank meta checks (CommStaticCheck / NCCLDynamicCheck equivalents)
# --------------------------------------------------------------------------

def static_check_meta(store, rank: int, world_size: int, op: str, seq: int,
                      shape, dtype, generation: str = "0",
                      timeout: float = 60.0) -> None:
    """Verify every rank brings the same (shape, dtype) to collective `op`.

    Reference `CommStaticCheck::CheckShape` (static_check.h:24) runs on the
    root's meta; here every rank publishes its meta under the op's sequence
    key and rank 0 cross-checks all of them, so the error names the
    offending rank instead of crashing inside the collective.
    """
    me = json.dumps({"shape": list(shape), "dtype": str(dtype)})
    base = f"__meta__/{generation}/{op}/{seq}"
    # Deferred GC, no extra barrier (the store would otherwise grow one key
    # per collective).  Own meta of seq-1 is safe to free: verdict seq-1
    # existed only after rank 0 read every meta.  The verdict must age one
    # more round (free seq-2): a slow rank may still be waiting on verdict
    # seq-1 while rank 0 enters seq.
    try:
        if seq > 0:
            store.delete_key(f"__meta__/{generation}/{op}/{seq - 1}/{rank}")
        if rank == 0 and seq > 1:
            store.delete_key(f"__meta__/{generation}/{op}/{seq - 2}/verdict")
    except Exception:
        pass
    store.set(f"{base}/{rank}", me.encode())
    if rank == 0:
        metas = {}
        for r in range(world_size):
            store.wait(f"{base}/{r}", timeout=timeout)
            metas[r] = json.loads(store.get(f"{base}/{r}").decode())
        ref = metas[0]
        for r, m in metas.items():
            if m != ref:
                store.set(f"{base}/verdict",
                          f"rank {r} meta {m} != rank 0 meta {ref}".encode())
                raise RuntimeError(
                    f"comm_static_check failed for '{op}' seq {seq}: "
                    f"rank {r} brings {m}, rank 0 brings {ref}")
        store.set(f"{base}/verdict", b"ok")
    else:
        store.wait(f"{base}/verdict", timeout=timeout)
        verdict = store.get(f"{base}/verdict")
        if verdict != b"ok":
            raise RuntimeError(
                f"comm_static_check failed for '{op}' seq {seq}: "
                f"{verdict.decode()}")
